// Sanitizer harness for the C++ host runtime (SURVEY §5 race-detection
// axis: "TPU build: rely on C++ TSAN/ASAN in tests"). Exercises every
// extern-C entry point — hashing, partition permutation, slot-directory
// resolve (hit + miss + dedup paths), JSON-lines parsing incl. malformed
// input, and a multi-threaded framed-TCP data-plane roundtrip — under
// -fsanitize=address,undefined (make asan-test) and =thread
// (make tsan-test). Plain asserts; exit 0 = clean.
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void ah_hash_u64(const uint64_t*, uint64_t*, int64_t);
void ah_hash_combine(uint64_t*, const uint64_t*, int64_t);
void ah_hash_f64(const double*, uint64_t*, int64_t);
int ah_partition(const uint64_t*, int64_t, int32_t, int64_t*, int64_t*);
int64_t ah_dir_resolve(const int64_t*, const int64_t*, int64_t,
                       const uint64_t*, const int64_t*, const int64_t*,
                       int64_t, int64_t, const int64_t*, const int64_t*,
                       int64_t*, int64_t*, uint64_t*, int64_t*, int64_t*);
int64_t ah_parse_json_lines(const char*, int64_t, int32_t, const char*,
                            const int32_t*, int64_t, int64_t**, double**,
                            uint8_t**, int64_t**, char**, int64_t*);
void ah_free(void*);
int dp_listen(const char*, int);
int dp_bound_port(int);
int dp_accept(int);
int dp_connect(const char*, int, int, int);
int dp_send_frame(int, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  const char*, uint32_t);
int dp_recv_header(int, uint32_t*);
int dp_recv_payload(int, char*, uint32_t);
void dp_close(int);
}

static void test_hashing() {
  const int64_t n = 1000;
  std::vector<uint64_t> in(n), a(n), b(n);
  for (int64_t i = 0; i < n; i++) in[i] = (uint64_t)(i * 37);
  ah_hash_u64(in.data(), a.data(), n);
  ah_hash_u64(in.data(), b.data(), n);
  for (int64_t i = 0; i < n; i++) assert(a[i] == b[i]);
  assert(a[0] != a[1]);
  ah_hash_combine(a.data(), b.data(), n);
  for (int64_t i = 0; i < n; i++) assert(a[i] != b[i]);
  std::vector<double> f(n);
  for (int64_t i = 0; i < n; i++) f[i] = i * 0.5 - 10.0;
  f[1] = -0.0;  // must hash like +0.0
  f[2] = 0.0;
  ah_hash_f64(f.data(), a.data(), n);
  assert(a[1] == a[2]);
}

static void test_partition() {
  const int64_t n = 4096;
  const int32_t nd = 8;
  std::vector<uint64_t> h(n);
  for (int64_t i = 0; i < n; i++) h[i] = (uint64_t)(i * 2654435761u);
  std::vector<int64_t> perm(n), offsets(nd + 1);
  assert(ah_partition(h.data(), n, nd, perm.data(), offsets.data()) == 0);
  assert(offsets[0] == 0 && offsets[nd] == n);
  std::vector<char> seen(n, 0);
  for (int64_t i = 0; i < n; i++) {
    assert(perm[i] >= 0 && perm[i] < n && !seen[perm[i]]);
    seen[perm[i]] = 1;
  }
  for (int32_t d = 0; d < nd; d++) assert(offsets[d] <= offsets[d + 1]);
}

static void test_dir_resolve() {
  const int64_t n = 512, hcap = 2048, nslots = 1024;
  std::vector<int64_t> keys(n), bins(n);
  for (int64_t i = 0; i < n; i++) { keys[i] = i % 100; bins[i] = i % 3; }
  // empty directory: everything misses, deduped to distinct (key,bin)
  std::vector<uint64_t> hcode(hcap, 0);
  std::vector<int64_t> hbin(hcap, -1), hslot(hcap, -1);
  std::vector<int64_t> slot_keys(nslots, -1), slot_bins(nslots, -1);
  std::vector<int64_t> out_slots(n), miss_ord(n), miss_keys(n), miss_bins(n);
  std::vector<uint64_t> miss_codes(n);
  int64_t m = ah_dir_resolve(keys.data(), bins.data(), n, hcode.data(),
                             hbin.data(), hslot.data(), hcap, 0,
                             slot_keys.data(), slot_bins.data(),
                             out_slots.data(), miss_ord.data(),
                             miss_codes.data(), miss_keys.data(),
                             miss_bins.data());
  assert(m == 300);  // 100 keys x 3 bins distinct misses
  for (int64_t i = 0; i < n; i++) assert(out_slots[i] < 0);
  for (int64_t i = 0; i < n; i++) assert(miss_ord[i] >= 0 && miss_ord[i] < m);
}

static void test_json() {
  const char* data =
      "{\"a\": 1, \"b\": 2.5, \"c\": true, \"d\": \"x\"}\n"
      "{\"a\": -7, \"b\": 0.25, \"c\": false, \"d\": \"hello world\"}\n";
  const char names[] = "a\0b\0c\0d\0";
  int32_t kinds[4] = {0, 1, 2, 3};
  std::vector<int64_t> ca(16), offs(17);
  std::vector<double> cb(16);
  std::vector<uint8_t> cc(16);
  int64_t* iptrs[4] = {ca.data(), nullptr, nullptr, nullptr};
  double* fptrs[4] = {nullptr, cb.data(), nullptr, nullptr};
  uint8_t* bptrs[4] = {nullptr, nullptr, cc.data(), nullptr};
  int64_t* optrs[4] = {nullptr, nullptr, nullptr, offs.data()};
  char* arena = nullptr;
  int64_t arena_len = 0;
  int64_t rows = ah_parse_json_lines(data, (int64_t)strlen(data), 4,
                                     names, kinds, 16, iptrs, fptrs, bptrs,
                                     optrs, &arena, &arena_len);
  assert(rows == 2);
  assert(ca[0] == 1 && ca[1] == -7);
  assert(cb[0] == 2.5 && cb[1] == 0.25);
  assert(cc[0] == 1 && cc[1] == 0);
  assert(arena_len > 0);
  assert(strncmp(arena + offs[0], "x", 1) == 0);
  ah_free(arena);
  // malformed input: error, no leak, no crash
  const char* bad = "{\"a\": }\n";
  arena = nullptr;
  int64_t r2 = ah_parse_json_lines(bad, (int64_t)strlen(bad), 4, names,
                                   kinds, 16, iptrs, fptrs, bptrs, optrs,
                                   &arena, &arena_len);
  assert(r2 < 0);
  if (arena) ah_free(arena);
}

static void test_data_plane() {
  int lfd = dp_listen("127.0.0.1", 0);
  assert(lfd >= 0);
  int port = dp_bound_port(lfd);
  assert(port > 0);
  const int kFrames = 200;
  std::thread server([&] {
    int c = dp_accept(lfd);
    assert(c >= 0);
    uint32_t hdr[6];
    for (int i = 0; i < kFrames; i++) {
      assert(dp_recv_header(c, hdr) == 0);
      assert((int)hdr[0] == i && hdr[4] == 0u);
      std::vector<char> payload(hdr[5]);
      if (hdr[5]) assert(dp_recv_payload(c, payload.data(), hdr[5]) == 0);
      if (hdr[5]) assert(payload[0] == (char)('a' + i % 26));
    }
    assert(dp_recv_header(c, hdr) == -1);  // clean close
    dp_close(c);
  });
  int fd = dp_connect("127.0.0.1", port, 10, 20);
  assert(fd >= 0);
  for (int i = 0; i < kFrames; i++) {
    std::vector<char> payload(1 + i % 512, (char)('a' + i % 26));
    assert(dp_send_frame(fd, (uint32_t)i, 1, 2, 3, 0, payload.data(),
                         (uint32_t)payload.size()) == 0);
  }
  dp_close(fd);
  server.join();
  dp_close(lfd);
}

int main() {
  test_hashing();
  test_partition();
  test_dir_resolve();
  test_json();
  test_data_plane();
  printf("host_test OK\n");
  return 0;
}
