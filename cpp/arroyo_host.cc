// arroyo-tpu C++ host runtime.
//
// Native equivalents of the reference engine's hot host-side paths, which in
// the reference are Rust inside arroyo-worker/arroyo-operator:
//   - 64-bit key hashing            (context.rs:512 create_hashes analog;
//                                    splitmix64 mix, matching hashing.py)
//   - keyed repartition permutation (context.rs:502-556 repartition)
//   - JSON-lines columnar parsing   (arroyo-formats de.rs hot loop)
//   - framed TCP data plane         (worker/src/network_manager.rs: 24-byte
//                                    header + payload per frame)
//
// Exposed as a plain C ABI consumed via ctypes (arroyo_tpu/native). The
// compute path stays JAX/XLA; this library owns the byte-shoveling
// around it.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- hashing

static const uint64_t C1 = 0x9E3779B97F4A7C15ull;
static const uint64_t C2 = 0xBF58476D1CE4E5B9ull;
static const uint64_t C3 = 0x94D049BB133111EBull;

static inline uint64_t splitmix64(uint64_t x) {
  uint64_t z = x + C1;
  z = (z ^ (z >> 30)) * C2;
  z = (z ^ (z >> 27)) * C3;
  return z ^ (z >> 31);
}

// out[i] = splitmix64(in[i])
void ah_hash_u64(const uint64_t* in, uint64_t* out, int64_t n) {
  for (int64_t i = 0; i < n; i++) out[i] = splitmix64(in[i]);
}

// h[i] = splitmix64(h[i] ^ (h2[i] + C1)) — column combine (hashing.py:74)
void ah_hash_combine(uint64_t* h, const uint64_t* h2, int64_t n) {
  for (int64_t i = 0; i < n; i++) h[i] = splitmix64(h[i] ^ (h2[i] + C1));
}

// float canonicalization: -0.0 -> 0.0, then bitcast (hashing.py:60-62)
void ah_hash_f64(const double* in, uint64_t* out, int64_t n) {
  for (int64_t i = 0; i < n; i++) {
    double v = in[i] == 0.0 ? 0.0 : in[i];
    uint64_t bits;
    memcpy(&bits, &v, 8);
    out[i] = splitmix64(bits);
  }
}

// ------------------------------------------------------------ repartition

// Counting-sort permutation of rows by destination subtask.
// dests_out[i] = min(hash[i] / size, n_dest-1); perm is a stable ordering of
// row indices grouped by destination; offsets[d]..offsets[d+1] delimit
// destination d's rows in perm. Returns 0 on success.
int ah_partition(const uint64_t* hashes, int64_t n_rows, int32_t n_dest,
                 int64_t* perm, int64_t* offsets /* n_dest+1 */) {
  if (n_dest <= 0) return -1;
  if (n_dest == 1) {
    // size would be 2^64 (wraps to 0): everything goes to destination 0
    for (int64_t i = 0; i < n_rows; i++) perm[i] = i;
    offsets[0] = 0;
    offsets[1] = n_rows;
    return 0;
  }
  const uint64_t size = 0xFFFFFFFFFFFFFFFFull / (uint64_t)n_dest + 1;
  // counts
  for (int32_t d = 0; d <= n_dest; d++) offsets[d] = 0;
  // reuse perm as scratch for per-row destination to avoid a second pass
  for (int64_t i = 0; i < n_rows; i++) {
    uint64_t d = hashes[i] / size;
    if (d >= (uint64_t)n_dest) d = n_dest - 1;
    perm[i] = (int64_t)d;
    offsets[d + 1]++;
  }
  for (int32_t d = 0; d < n_dest; d++) offsets[d + 1] += offsets[d];
  // stable scatter
  int64_t* cursor = (int64_t*)malloc(sizeof(int64_t) * n_dest);
  if (!cursor) return -2;
  for (int32_t d = 0; d < n_dest; d++) cursor[d] = offsets[d];
  // second buffer for output permutation
  int64_t* out = (int64_t*)malloc(sizeof(int64_t) * (n_rows ? n_rows : 1));
  if (!out) { free(cursor); return -2; }
  for (int64_t i = 0; i < n_rows; i++) {
    int64_t d = perm[i];
    out[cursor[d]++] = i;
  }
  memcpy(perm, out, sizeof(int64_t) * n_rows);
  free(out);
  free(cursor);
  return 0;
}

// -------------------------------------------------------- slot directory

// One-pass resolve over the BinSlotDirectory's open-addressing arrays
// (arroyo_tpu/ops/slot_agg.py BinSlotDirectory: hcode/hbin/hslot parallel
// arrays). Probe semantics mirror the numpy fallback lookup_or_assign:
// code = splitmix64(key ^ bin*C1); a live entry (hslot >= 0 && hbin >=
// boundary) with matching code resolves (identity-checked against
// slot_keys/slot_bins; mismatch = 64-bit collision -> -2); the first
// non-live probe position means the group has no slot yet -> MISS.
//
// Misses are deduplicated by code in stream order: out_slots[i] = -1 and
// miss_ord[i] = index into miss_codes/miss_keys/miss_bins (length = return
// value) so Python can allocate each first-seen group exactly once via
// BinSlotDirectory.lookup_or_assign and scatter the new slots back through
// miss_ord. Returns the miss count, -2 on identity collision, -3 when a
// probe wraps the full table (caller falls back to numpy).
int64_t ah_dir_resolve(
    const int64_t* keys, const int64_t* bins, int64_t n,
    const uint64_t* hcode, const int64_t* hbin, const int64_t* hslot,
    int64_t hcap, int64_t boundary,
    const int64_t* slot_keys, const int64_t* slot_bins,
    int64_t* out_slots, int64_t* miss_ord,
    uint64_t* miss_codes, int64_t* miss_keys, int64_t* miss_bins) {
  const uint64_t hmask = (uint64_t)hcap - 1;
  // local dedup table for missed codes (ord = -1 marks empty)
  int64_t dcap = 64;
  while (dcap < 2 * n) dcap <<= 1;
  const uint64_t dmask = (uint64_t)dcap - 1;
  uint64_t* dcode = (uint64_t*)malloc(sizeof(uint64_t) * dcap);
  int64_t* dord = (int64_t*)malloc(sizeof(int64_t) * dcap);
  if (!dcode || !dord) { free(dcode); free(dord); return -4; }
  for (int64_t j = 0; j < dcap; j++) dord[j] = -1;
  int64_t m = 0;
  int64_t rc = 0;
  for (int64_t i = 0; i < n; i++) {
    const int64_t key = keys[i];
    const int64_t bin = bins[i];
    const uint64_t code = splitmix64((uint64_t)key ^ ((uint64_t)bin * C1));
    uint64_t h = code & hmask;
    int64_t slot = -1;
    bool miss = false;
    int64_t step = 0;
    for (; step < hcap; step++) {
      if (hslot[h] < 0 || hbin[h] < boundary) { miss = true; break; }
      if (hcode[h] == code) {
        const int64_t s = hslot[h];
        if (slot_keys[s] != key || slot_bins[s] != bin) { rc = -2; goto done; }
        slot = s;
        break;
      }
      h = (h + 1) & hmask;
    }
    if (slot < 0 && !miss) { rc = -3; goto done; }  // table wrapped
    if (miss) {
      uint64_t dh = code & dmask;
      while (dord[dh] >= 0 && dcode[dh] != code) dh = (dh + 1) & dmask;
      if (dord[dh] < 0) {
        dcode[dh] = code;
        dord[dh] = m;
        miss_codes[m] = code;
        miss_keys[m] = key;
        miss_bins[m] = bin;
        m++;
      }
      miss_ord[i] = dord[dh];
    }
    out_slots[i] = slot;
  }
  rc = m;
done:
  free(dcode);
  free(dord);
  return rc;
}

// ------------------------------------------------------------- JSON lines
//
// Flat-object parser for a fixed schema. Column kinds:
//   0 = int64, 1 = float64, 2 = bool, 3 = string, 4 = skip/ignore
// For string columns the caller gets (offsets into a shared byte arena).
// Missing keys yield 0 / NaN / false / empty. Returns rows parsed, or
// -(line_index+1) on malformed input.

struct StrArena {
  char* data;
  int64_t len;
  int64_t cap;
};

static int arena_push(StrArena* a, const char* s, int64_t n) {
  if (a->len + n > a->cap) {
    int64_t ncap = a->cap * 2;
    if (ncap < a->len + n) ncap = a->len + n + 4096;
    char* nd = (char*)realloc(a->data, ncap);
    if (!nd) return -1;
    a->data = nd;
    a->cap = ncap;
  }
  memcpy(a->data + a->len, s, n);
  a->len += n;
  return 0;
}

static const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
  return p;
}

// parse a JSON string starting at the opening quote; unescapes into buf
// (caller-sized >= input length). Returns pointer past closing quote, or
// nullptr on error; *out_len = unescaped length.
static const char* parse_string(const char* p, const char* end, char* buf,
                                int64_t* out_len) {
  if (p >= end || *p != '"') return nullptr;
  p++;
  int64_t n = 0;
  while (p < end && *p != '"') {
    if (*p == '\\' && p + 1 < end) {
      p++;
      char c = *p++;
      switch (c) {
        case 'n': buf[n++] = '\n'; break;
        case 't': buf[n++] = '\t'; break;
        case 'r': buf[n++] = '\r'; break;
        case 'b': buf[n++] = '\b'; break;
        case 'f': buf[n++] = '\f'; break;
        case '"': buf[n++] = '"'; break;
        case '\\': buf[n++] = '\\'; break;
        case '/': buf[n++] = '/'; break;
        case 'u': {
          if (p + 4 > end) return nullptr;
          unsigned cp = 0;
          for (int k = 0; k < 4; k++) {
            char h = p[k];
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return nullptr;
          }
          p += 4;
          // utf-8 encode (BMP only; surrogate pairs pass through as-is)
          if (cp < 0x80) buf[n++] = (char)cp;
          else if (cp < 0x800) {
            buf[n++] = (char)(0xC0 | (cp >> 6));
            buf[n++] = (char)(0x80 | (cp & 0x3F));
          } else {
            buf[n++] = (char)(0xE0 | (cp >> 12));
            buf[n++] = (char)(0x80 | ((cp >> 6) & 0x3F));
            buf[n++] = (char)(0x80 | (cp & 0x3F));
          }
          break;
        }
        default: return nullptr;
      }
    } else {
      buf[n++] = *p++;
    }
  }
  if (p >= end) return nullptr;
  *out_len = n;
  return p + 1;  // past closing quote
}

// skip any JSON value (for unknown keys / nested objects)
static const char* skip_value(const char* p, const char* end) {
  p = skip_ws(p, end);
  if (p >= end) return nullptr;
  if (*p == '"') {
    p++;
    while (p < end && *p != '"') {
      if (*p == '\\') p++;
      p++;
    }
    return p < end ? p + 1 : nullptr;
  }
  if (*p == '{' || *p == '[') {
    char open = *p, close = (*p == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    while (p < end) {
      if (in_str) {
        if (*p == '\\') p++;
        else if (*p == '"') in_str = false;
      } else if (*p == '"') in_str = true;
      else if (*p == open) depth++;
      else if (*p == close) {
        depth--;
        if (depth == 0) return p + 1;
      }
      p++;
    }
    return nullptr;
  }
  while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
         *p != '\n' && *p != '\t' && *p != '\r')
    p++;
  return p;
}

// data: newline-separated JSON objects. Schema: n_cols columns with names
// (concatenated, NUL-separated) and kinds. Outputs: per-column arrays sized
// max_rows; string columns write (str_offsets[col][row+1] ends) into one
// shared arena returned via *arena_out/*arena_len (caller frees with
// ah_free). Bool columns are uint8. null -> 0/NaN/false/empty.
int64_t ah_parse_json_lines(const char* data, int64_t data_len,
                            int32_t n_cols, const char* names_blob,
                            const int32_t* kinds, int64_t max_rows,
                            int64_t** int_cols, double** f64_cols,
                            uint8_t** bool_cols, int64_t** str_offsets,
                            char** arena_out, int64_t* arena_len) {
  // resolve column names
  const char* names[64];
  int64_t name_lens[64];
  if (n_cols > 64) return -1000000;
  {
    const char* p = names_blob;
    for (int32_t c = 0; c < n_cols; c++) {
      names[c] = p;
      name_lens[c] = strlen(p);
      p += name_lens[c] + 1;
    }
  }
  StrArena arena = {(char*)malloc(4096), 0, 4096};
  if (!arena.data) return -1000001;
  char* strbuf = (char*)malloc(data_len + 8);
  if (!strbuf) { free(arena.data); return -1000001; }

  // initialize string offsets row 0
  for (int32_t c = 0; c < n_cols; c++)
    if (kinds[c] == 3) str_offsets[c][0] = 0;

  const char* p = data;
  const char* end = data + data_len;
  int64_t row = 0;
  int64_t line_no = 0;
  while (p < end && row < max_rows) {
    const char* line_end = (const char*)memchr(p, '\n', end - p);
    if (!line_end) line_end = end;
    const char* q = skip_ws(p, line_end);
    if (q == line_end) { p = line_end + 1; line_no++; continue; }
    if (*q != '{') goto fail;
    q++;
    // defaults for this row
    for (int32_t c = 0; c < n_cols; c++) {
      switch (kinds[c]) {
        case 0: int_cols[c][row] = 0; break;
        case 1: f64_cols[c][row] = __builtin_nan(""); break;
        case 2: bool_cols[c][row] = 0; break;
        case 3: str_offsets[c][row + 1] = arena.len; break;
        default: break;
      }
    }
    while (true) {
      q = skip_ws(q, line_end);
      if (q < line_end && *q == '}') { q++; break; }
      int64_t klen;
      q = parse_string(q, line_end, strbuf, &klen);
      if (!q) goto fail;
      q = skip_ws(q, line_end);
      if (q >= line_end || *q != ':') goto fail;
      q++;
      q = skip_ws(q, line_end);
      // find the column
      int32_t col = -1;
      for (int32_t c = 0; c < n_cols; c++) {
        if (name_lens[c] == klen && memcmp(names[c], strbuf, klen) == 0) {
          col = c;
          break;
        }
      }
      if (col < 0 || kinds[col] == 4) {
        q = skip_value(q, line_end);
        if (!q) goto fail;
      } else if (kinds[col] == 3) {
        if (q < line_end && *q == '"') {
          int64_t slen;
          q = parse_string(q, line_end, strbuf, &slen);
          if (!q) goto fail;
          if (arena_push(&arena, strbuf, slen) != 0) goto fail;
        } else {
          // null / non-string: empty string
          q = skip_value(q, line_end);
          if (!q) goto fail;
        }
        str_offsets[col][row + 1] = arena.len;
      } else if (q < line_end && (*q == 'n')) {  // null
        q = skip_value(q, line_end);
        if (!q) goto fail;
      } else if (kinds[col] == 2) {
        if (q + 4 <= line_end && memcmp(q, "true", 4) == 0) {
          bool_cols[col][row] = 1;
          q += 4;
        } else if (q + 5 <= line_end && memcmp(q, "false", 5) == 0) {
          bool_cols[col][row] = 0;
          q += 5;
        } else goto fail;
      } else {
        char* numend;
        if (kinds[col] == 0) {
          long long v = strtoll(q, &numend, 10);
          if (numend == q) goto fail;
          // float-typed input into int column: fall back to strtod
          if (numend < line_end && (*numend == '.' || *numend == 'e' || *numend == 'E')) {
            double dv = strtod(q, &numend);
            v = (long long)dv;
          }
          int_cols[col][row] = v;
        } else {
          double v = strtod(q, &numend);
          if (numend == q) goto fail;
          f64_cols[col][row] = v;
        }
        q = numend;
      }
      q = skip_ws(q, line_end);
      if (q < line_end && *q == ',') q++;
    }
    row++;
    line_no++;
    p = line_end + 1;
  }
  *arena_out = arena.data;
  *arena_len = arena.len;
  free(strbuf);
  return row;

fail:
  free(arena.data);
  free(strbuf);
  return -(line_no + 1);
}

void ah_free(void* p) { free(p); }

// -------------------------------------------------------------- data plane
//
// Frame layout (reference network_manager.rs:102-162 — 24-byte LE header):
//   u32 src_op | u32 src_subtask | u32 dst_op | u32 dst_subtask |
//   u32 msg_type | u32 len        then `len` payload bytes.

struct FrameHeader {
  uint32_t src_op, src_subtask, dst_op, dst_subtask, msg_type, len;
};

static int read_full(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  size_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, p + got, n - got, 0);
    if (r == 0) return -1;  // peer closed
    if (r < 0) {
      if (errno == EINTR) continue;
      return -2;
    }
    got += (size_t)r;
  }
  return 0;
}

static int write_full(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -2;
    }
    sent += (size_t)r;
  }
  return 0;
}

int dp_listen(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) { close(fd); return -3; }
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) { close(fd); return -4; }
  if (listen(fd, 128) != 0) { close(fd); return -5; }
  return fd;
}

int dp_bound_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, (sockaddr*)&addr, &len) != 0) return -1;
  return ntohs(addr.sin_port);
}

int dp_accept(int listen_fd) {
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

int dp_connect(const char* host, int port, int retries, int backoff_ms) {
  for (int attempt = 0; attempt <= retries; attempt++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) { close(fd); return -3; }
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    close(fd);
    usleep((useconds_t)backoff_ms * 1000 * (attempt + 1));
  }
  return -2;
}

int dp_send_frame(int fd, uint32_t src_op, uint32_t src_sub, uint32_t dst_op,
                  uint32_t dst_sub, uint32_t msg_type, const char* payload,
                  uint32_t len) {
  FrameHeader h{src_op, src_sub, dst_op, dst_sub, msg_type, len};
  if (write_full(fd, &h, sizeof(h)) != 0) return -1;
  if (len && write_full(fd, payload, len) != 0) return -1;
  return 0;
}

// Two-phase receive so the caller can size the payload buffer exactly:
// dp_recv_header fills out_header[6] (src_op, src_sub, dst_op, dst_sub,
// msg_type, len); returns 0, -1 on clean close, -2 on error. Then
// dp_recv_payload reads exactly `len` bytes.
int dp_recv_header(int fd, uint32_t* out_header) {
  FrameHeader h;
  int r = read_full(fd, &h, sizeof(h));
  if (r != 0) return r == -1 ? -1 : -2;
  out_header[0] = h.src_op;
  out_header[1] = h.src_subtask;
  out_header[2] = h.dst_op;
  out_header[3] = h.dst_subtask;
  out_header[4] = h.msg_type;
  out_header[5] = h.len;
  return 0;
}

int dp_recv_payload(int fd, char* payload, uint32_t len) {
  if (len == 0) return 0;
  return read_full(fd, payload, len);
}

void dp_close(int fd) { close(fd); }

}  // extern "C"
