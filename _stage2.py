import time, sys, numpy as np
sys.path.insert(0, "/root/repo")
import arroyo_tpu
from arroyo_tpu import config as cfg
import bench
arroyo_tpu._load_operators()
cfg.update({"pipeline.chaining.enabled": True, "device.table-capacity": 65536,
            "device.emit-capacity": 8192, "worker.queue-size": 131072,
            "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints"})

from arroyo_tpu.ops import slot_agg as sa
from arroyo_tpu import native
T = {}
def tick(k, t0):
    T[k] = T.get(k, 0.0) + (time.perf_counter() - t0)

orig_step_build = sa.SlotAggregator._update_chunk
def timed_update(self, key_u64, bins, vals):
    t0 = time.perf_counter()
    m = len(key_u64)
    ku = np.ascontiguousarray(key_u64, dtype=np.uint64); ks = ku.view(np.int64)
    b64 = np.ascontiguousarray(bins, dtype=np.int64)
    d = self.directory
    tick("u.prep", t0); t0 = time.perf_counter()
    res = native.dir_resolve(ks, b64, d.hcode, d.hbin, d.hslot, d.boundary,
                             d.slot_keys, d.slot_bins)
    tick("u.dir_resolve", t0); t0 = time.perf_counter()
    row_slots, miss_ord, mc, mk, mb = res
    if len(mc):
        slots_new = d.lookup_or_assign(mc, mk, mb)
        neg = row_slots < 0
        row_slots[neg] = slots_new[miss_ord[neg]]
    tick("u.alloc", t0); t0 = time.perf_counter()
    spill_rows = row_slots < 0
    assert not spill_rows.any()
    B = self.batch_cap
    if m == B:
        slots = row_slots
        vs = [np.asarray(v, dtype=dt) for v, dt in zip(vals, self.acc_dtypes)]
    else:
        slots = np.full(B, self.cap, dtype=np.int64); slots[:m] = row_slots
        vs = []
        for v, k_, dt in zip(vals, self.acc_kinds, self.acc_dtypes):
            arr = np.full(B, sa._identity(k_, dt), dtype=dt); arr[:m] = v; vs.append(arr)
    tick("u.pad", t0); t0 = time.perf_counter()
    self.state = self._step(self.state, slots, tuple(vs))
    tick("u.step_dispatch", t0)
sa.SlotAggregator._update_chunk = timed_update

orig_es = sa.SlotAggregator.extract_start
def timed_es(self, *a):
    t0 = time.perf_counter()
    r = orig_es(self, *a)
    tick("extract_dispatch", t0)
    return r
sa.SlotAggregator.extract_start = timed_es

from arroyo_tpu.windows import tumbling as tw
for name, key in [("process_batch", "agg.process"), ("_drain_pending", "agg.drain")]:
    orig = getattr(tw.TumblingAggregate, name)
    def mk(orig, key):
        def f(self, *a, **k):
            t0 = time.perf_counter()
            r = orig(self, *a, **k)
            tick(key, t0)
            return r
        return f
    setattr(tw.TumblingAggregate, name, mk(orig, key))

bench.run_once("jax", 50_000, batch_size=32768)
T.clear()
wall, n, rows = bench.run_once("jax", 2_000_000, batch_size=32768)
print(f"{n} events in {wall:.2f}s = {n/wall:,.0f} ev/s")
for k, v in sorted(T.items(), key=lambda kv: -kv[1]):
    print(f"  {k:20s} {v*1000:8.1f} ms")
