import time, sys, numpy as np
sys.path.insert(0, "/root/repo")
import arroyo_tpu
from arroyo_tpu import config as cfg
arroyo_tpu._load_operators()
cfg.update({"device.table-capacity": 65536, "checkpoint.storage-url": "/tmp/ck"})
from arroyo_tpu.ops.slot_agg import SlotAggregator
from arroyo_tpu import native

agg = SlotAggregator(("max","count","max"), (np.int64,np.int64,np.int64),
                     cap=65536, batch_cap=32768, emit_cap=8192, backend="jax",
                     region_size=2048)
rng = np.random.default_rng(0)
B = 32768
T = {}
def tick(k, t0):
    T[k] = T.get(k, 0.0) + (time.perf_counter() - t0)

# synthetic q7-like stream: 3.3 bins per batch advancing, ~3.1k keys/bin
for it in range(31):
    base_bin = it * 33 // 10
    keys = rng.integers(0, 3100, B).astype(np.uint64) + np.uint64(1000)
    bins = (base_bin + rng.integers(0, 4, B)).astype(np.int32)
    vals = [rng.integers(100, 10_000_000, B).astype(np.int64),
            np.ones(B, dtype=np.int64), keys.view(np.int64).copy()]
    t0 = time.perf_counter()
    ku = np.ascontiguousarray(keys, dtype=np.uint64); ks = ku.view(np.int64)
    b64 = np.ascontiguousarray(bins, dtype=np.int64)
    tick("prep", t0)
    d = agg.directory
    t0 = time.perf_counter()
    res = native.dir_resolve(ks, b64, d.hcode, d.hbin, d.hslot, d.boundary,
                             d.slot_keys, d.slot_bins)
    tick("dir_resolve", t0)
    row_slots, miss_ord, mc, mk, mb = res
    t0 = time.perf_counter()
    if len(mc):
        slots_new = d.lookup_or_assign(mc, mk, mb)
        neg = row_slots < 0
        row_slots[neg] = slots_new[miss_ord[neg]]
    tick("alloc", t0)
    t0 = time.perf_counter()
    vs = [np.asarray(v, dtype=dt) for v, dt in zip(vals, agg.acc_dtypes)]
    tick("vals", t0)
    t0 = time.perf_counter()
    agg.state = agg._step(agg.state, row_slots, tuple(vs))
    tick("step_dispatch", t0)
    # close a bin every ~3 batches like the real stream
    if it % 3 == 2:
        t0 = time.perf_counter()
        h = agg.extract_start(0, base_bin, base_bin)
        tick("extract_dispatch", t0)
        t0 = time.perf_counter()
        h.result()
        tick("extract_fetch", t0)
import jax
jax.block_until_ready(agg.state)
for k, v in T.items():
    print(f"  {k:18s} {v*1000:8.1f} ms total  {v/31*1000:6.2f} ms/batch")
