import time, threading, numpy as np, jax, jax.numpy as jnp

@jax.jit
def tiny(x): return x + 1
small = jnp.zeros(2048*3, jnp.int32); tiny(small).block_until_ready()

stop = False
count = [0]
def counter():
    while not stop:
        count[0] += 1

# baseline counting rate
t = threading.Thread(target=counter); t.start()
time.sleep(1.0); stop = True; t.join()
base_rate = count[0]
print(f"counting alone: {base_rate/1e6:.2f} M/s")

stop = False; count = [0]
t = threading.Thread(target=counter); t.start()
t0 = time.perf_counter(); n_f = 0
while time.perf_counter() - t0 < 1.0:
    h = tiny(small); h.copy_to_host_async(); np.asarray(h); n_f += 1
stop = True; t.join()
print(f"counting during fetches: {count[0]/1e6:.2f} M/s ({count[0]/base_rate*100:.0f}% of baseline), {n_f} fetches")
