import time, sys, numpy as np
import arroyo_tpu
from arroyo_tpu import config as cfg
sys.path.insert(0, "/root/repo")
import bench

arroyo_tpu._load_operators()
cfg.update({
    "pipeline.source-batch-size": 32768,
    "pipeline.chaining.enabled": True,
    "device.batch-capacity": 32768,
    "device.table-capacity": 65536,
    "device.emit-capacity": 8192,
    "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints",
})

T = {}
def wrap(obj, name, key):
    orig = getattr(obj, name)
    def timed(*a, **k):
        t0 = time.perf_counter()
        r = orig(*a, **k)
        T[key] = T.get(key, 0.0) + (time.perf_counter() - t0)
        return r
    setattr(obj, name, timed)

from arroyo_tpu.connectors import nexmark as nx
from arroyo_tpu.windows import tumbling as tw
from arroyo_tpu.ops import slot_agg as sa
from arroyo_tpu.operators import builtin as bi

wrap(nx.NexmarkSource, "_generate", "source_generate")
wrap(bi.ValueOperator, "process_batch", "value_op_total")
wrap(bi.KeyOperator, "process_batch", "key_op_total")
wrap(tw.TumblingAggregate, "process_batch", "agg_process_total")
wrap(sa.SlotAggregator, "_update_chunk", "agg_update_chunk")
wrap(sa.BinSlotDirectory, "lookup_or_assign", "dir_lookup")
wrap(sa.SlotAggregator, "extract_start", "close_dispatch")
wrap(sa.SlotExtractHandle, "result", "close_fetch_materialize")
wrap(tw.TumblingAggregate, "_emit_entries", "emit_entries")

# warmup
bench.run_once("jax", 50_000, batch_size=32768)
T.clear()
wall, n, rows = bench.run_once("jax", 1_000_000, batch_size=32768)
print(f"\n{n} events in {wall:.2f}s = {n/wall:,.0f} ev/s")
# note: nested keys overlap (update_chunk inside agg_process etc.)
for k, v in sorted(T.items(), key=lambda kv: -kv[1]):
    print(f"  {k:26s} {v*1000:8.1f} ms")
