"""Python scalar UDFs.

Equivalent of the reference's Python UDF support
(crates/arroyo-udf/arroyo-udf-python/src/lib.rs:30 PythonUDF — scalar
functions registered with the planner and evaluated row/batch-wise) without
the embedded-interpreter hop: UDFs here are plain Python callables registered
into a process-global registry the SQL planner consults for unknown function
names. Vectorized UDFs receive numpy arrays; scalar ones are wrapped with
np.vectorize-style row iteration.

Rust dylib UDFs (arroyo-udf-host) have no equivalent here by design: native
extension points go through the C++ host runtime instead (arroyo_tpu.native).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from .expr import Expr


@dataclass(frozen=True)
class UdfExpr(Expr):
    """Expression node calling a registered Python UDF."""

    udf_name: str
    fn: Callable
    vectorized: bool
    return_dtype: str
    args: tuple[Expr, ...]

    def eval_np(self, cols, n):
        import numpy as np

        vals = [a.eval_np(cols, n) for a in self.args]
        vals = [np.broadcast_to(np.asarray(v), (n,)) if not hasattr(v, "shape") or getattr(v, "shape", ()) == () else v for v in vals]
        if self.vectorized:
            return np.asarray(self.fn(*vals))
        out = [self.fn(*(v[i] for v in vals)) for i in range(n)]
        if self.return_dtype == "string":
            return np.array(out, dtype=object)
        from .batch import Field

        return np.array(out, dtype=Field("_", self.return_dtype).numpy_dtype())

    def eval_jnp(self, cols):
        raise NotImplementedError(f"python UDF {self.udf_name} cannot run on device")

    def columns(self):
        out = set()
        for a in self.args:
            out |= a.columns()
        return out


@dataclass
class Udf:
    name: str
    fn: Callable
    return_dtype: str
    vectorized: bool
    is_async: bool = False
    max_concurrency: int = 64
    ordered: bool = True

    def as_expr(self, args: tuple[Expr, ...]) -> UdfExpr:
        if self.is_async:
            from .sql.lexer import SqlError

            raise SqlError(
                f"async UDF {self.name!r} must be the outermost select expression"
            )
        return UdfExpr(self.name, self.fn, self.vectorized, self.return_dtype, args)


_REGISTRY: dict[str, Udf] = {}


def register_udf(
    name: str,
    fn: Optional[Callable] = None,
    *,
    return_dtype: str = "float64",
    vectorized: bool = False,
    is_async: bool = False,
    max_concurrency: int = 64,
    ordered: bool = True,
):
    """Register a Python scalar UDF usable from SQL. Decorator or direct call.

    register_udf("square", lambda x: x * x, return_dtype="int64", vectorized=True)
    """

    def inner(f: Callable) -> Callable:
        _REGISTRY[name.lower()] = Udf(
            name.lower(), f, return_dtype, vectorized, is_async, max_concurrency, ordered
        )
        return f

    if fn is not None:
        return inner(fn)
    return inner


def lookup_udf(name: str) -> Optional[Udf]:
    return _REGISTRY.get(name.lower())


@dataclass
class Udaf:
    """User-defined aggregate (reference: custom UDAFs in
    arroyo-planner/src/udafs.rs). The function receives the group's
    collected input values as one numpy array and returns a scalar; state
    between merges is the collected-value list (universally mergeable, like
    the reference materializing UDAF inputs). Supported where aggregation
    state is host-resident (session windows)."""

    name: str
    fn: Callable
    return_dtype: str


_UDAF_REGISTRY: dict[str, Udaf] = {}


def register_udaf(name: str, fn: Optional[Callable] = None, *,
                  return_dtype: str = "float64"):
    """Register a Python UDAF usable from SQL. Decorator or direct call.

    register_udaf("p95", lambda v: float(np.percentile(v, 95)))
    """

    def inner(f: Callable) -> Callable:
        _UDAF_REGISTRY[name.lower()] = Udaf(name.lower(), f, return_dtype)
        return f

    if fn is not None:
        return inner(fn)
    return inner


def lookup_udaf(name: str) -> Optional[Udaf]:
    return _UDAF_REGISTRY.get(name.lower())


def drop_udaf(name: str) -> None:
    _UDAF_REGISTRY.pop(name.lower(), None)


def drop_udf(name: str) -> None:
    _REGISTRY.pop(name.lower(), None)


def udfs() -> dict[str, Udf]:
    return dict(_REGISTRY)
