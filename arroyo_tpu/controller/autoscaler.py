"""Elastic autoscaler: the actuator closing the loop from health signals
to worker count (ROADMAP item 5; reference: the kubernetes scheduler of
PAPER.md §controller, and Enthuse's case — arXiv:2405.18168 — that a
streaming engine must adapt its parallelism to the workload rather than
be provisioned for the peak).

Every sensor already exists: the controller holds a merged per-operator
metrics snapshot (backpressure, queue-transit p99, watermark lag, sink
latency, profiler busy%) and per-job health rules with hysteresis
(obs/health.py). This module is the *decide* half the health monitors
deliberately stopped short of: evaluated once per supervision tick, it
turns sustained pressure into a target parallelism and actuates it
through the exact coordinated path a human rescale uses — take a final
checkpoint, drain the worker set, restore at the new scale
(``JobController`` Rescaling / ``_finish_rescale``). No second rescale
mechanism exists; the autoscaler just writes ``desired_parallelism``.

Most of the machinery here is rails, because an actuator without rails
turns one bad metric into an outage:

* **hysteresis** — scale up only after ``autoscaler.up-ticks``
  consecutive pressured evaluations; scale DOWN only after
  ``autoscaler.down-ticks`` consecutive ticks of *proven* headroom (low
  busy%, low backpressure, no pressure signal; absent metrics prove
  nothing and reset the streak).
* **cooldown** — after any worker-set (re)start — a completed rescale,
  a crash restore, first schedule — decisions freeze for
  ``autoscaler.cooldown-s``: post-restart metrics are warm-up noise.
* **bounds** — every target is clamped to
  ``autoscaler.min/max-parallelism`` *after* the decision (and after the
  ``autoscale_decide`` chaos hook, so a forced-bogus target proves the
  clamp).
* **backoff** — a scale attempt whose transition is disrupted (a worker
  dying mid-drain, a wedged drain escalating) arms an exponential
  backoff window (``backoff-base-s`` · ``backoff-multiplier``ⁿ, capped
  at ``backoff-max-s``); a cleanly completed scale resets the streak.
* **never scale blind** — no decisions unless the job is Running, and
  none mid-checkpoint-failure-streak (a rescale needs a fresh final
  checkpoint; wedging epochs mean it won't get one).

Surfaces: AUTOSCALE_DECISION / AUTOSCALE_STARTED / AUTOSCALE_DONE /
AUTOSCALE_BACKOFF job events, the ``arroyo_autoscaler_target`` gauge,
and a ``autoscaler`` detail block on ``GET /api/v1/jobs/<id>/health``.

The loop is wall-time injectable (``clock=``) so unit tests drive
cooldown/backoff with a fake clock and hand-fed snapshots — no sleeps.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..obs.health import _worst


@dataclass(frozen=True)
class Signal:
    """One scale-up pressure signal: a merged-snapshot observation
    compared against its ``autoscaler.*`` threshold (same shape as the
    health rules — the worst operator is the one that melts first)."""

    signal_id: str
    config_key: str
    default: float
    description: str
    observe: Callable[[dict], Optional[float]]

    def threshold(self) -> float:
        from ..config import config

        v = config().get(f"autoscaler.{self.config_key}")
        return float(v) if v is not None else self.default


UP_SIGNALS: tuple[Signal, ...] = (
    Signal("backpressure", "up-backpressure", 0.8,
           "worst-operator backpressure (queues near budget)",
           lambda m: _worst(m, "backpressure")),
    Signal("queue-transit", "up-queue-transit-p99-ms", 750.0,
           "worst-operator inbox transit p99",
           lambda m: _worst(m, "queue_transit_p99_ms")),
    Signal("watermark-lag", "up-watermark-lag-s", 30.0,
           "worst-operator watermark lag",
           lambda m: _worst(m, "watermark_lag_seconds")),
    Signal("sink-latency", "up-sink-latency-p99-s", 30.0,
           "sink end-to-end event latency p99",
           lambda m: _worst(m, "sink_event_latency_p99_s")),
)


class Autoscaler:
    """Per-job control loop owned by the JobController and evaluated on
    its supervision tick. ``evaluate`` returns a clamped target
    parallelism to actuate (or None); the controller owns actuation and
    reports the transition back via ``on_worker_set_started`` /
    ``on_scale_disrupted``."""

    def __init__(self, job_id: str,
                 emit: Optional[Callable[..., None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.job_id = job_id
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self._up_ticks = 0
        self._down_ticks = 0
        self._cooldown_until = 0.0
        self._backoff_until = 0.0
        self._failures = 0  # consecutive disrupted scale attempts
        self._disrupted = False  # the current in-flight transition broke
        self.in_flight: Optional[int] = None  # target being actuated
        self.last_decision: Optional[dict] = None
        self._last_noop: Optional[tuple] = None  # dedup key for at-bound
        self._last_blocked: Optional[tuple] = None  # dedup for fleet-block
        self._last_signals: list[dict] = []

    # ------------------------------------------------------------ config

    @staticmethod
    def _cfg(key: str, default):
        from ..config import config

        v = config().get(f"autoscaler.{key}")
        return default if v is None else v

    @classmethod
    def enabled(cls) -> bool:
        return bool(cls._cfg("enabled", False))

    # ---------------------------------------------------------- the loop

    def evaluate(self, metrics: Optional[dict], *, running: bool,
                 parallelism: int, ckpt_failures: int = 0) -> Optional[int]:
        """One supervision-tick evaluation. Returns the (rail-clamped)
        target parallelism the controller should actuate now, or None.
        Gates in order: enabled → job Running → no checkpoint-failure
        streak → hysteresis counters → cooldown/backoff → bounds."""
        if not self.enabled():
            self._up_ticks = self._down_ticks = 0
            return None
        if not running or self.in_flight is not None:
            # never scale while Recovering/Stopping/Rescaling/Evolving
            # (or while an evolution request is pending — the controller
            # gates `running` on that too) — the counters reset so a
            # breach mid-restore can't fire at the first post-restore
            # tick on stale conviction
            self._up_ticks = self._down_ticks = 0
            return None
        if ckpt_failures > 0:
            # mid-checkpoint-failure-streak: the drain checkpoint a
            # rescale needs is exactly what's currently wedging
            self._up_ticks = self._down_ticks = 0
            return None

        pressure, headroom = self._classify(metrics)
        if pressure:
            self._up_ticks += 1
            self._down_ticks = 0
        elif headroom:
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            self._up_ticks = self._down_ticks = 0

        up_n = max(1, int(self._cfg("up-ticks", 3)))
        down_n = max(1, int(self._cfg("down-ticks", 10)))
        raw: Optional[int] = None
        direction = None
        if self._up_ticks >= up_n:
            factor = float(self._cfg("up-factor", 2.0))
            raw = max(parallelism + 1, math.ceil(parallelism * factor))
            direction = "up"
        elif self._down_ticks >= down_n:
            factor = float(self._cfg("down-factor", 0.5))
            raw = min(parallelism - 1, int(math.floor(parallelism * factor)))
            raw = max(raw, 1)
            direction = "down"
        if raw is None:
            return None

        now = self._clock()
        if now < self._cooldown_until or now < self._backoff_until:
            # gated, not forgotten: the streak stays armed, so sustained
            # pressure fires on the first tick after the window expires
            self._up_ticks = min(self._up_ticks, up_n)
            self._down_ticks = min(self._down_ticks, down_n)
            return None

        # chaos hook: autoscale_decide may force a bogus target (the
        # min/max rails below must clamp it) or drop the decision; a
        # raising action models the decision computation blowing up, and
        # must cost at most this tick's decision — never the job
        from ..faults import InjectedFault, fault_point

        try:
            verdict = fault_point("autoscale_decide", key=self.job_id,
                                  target=raw, direction=direction)
        except InjectedFault:
            self._up_ticks = self._down_ticks = 0
            return None
        if verdict is not None:
            action, arg = verdict
            if action == "drop":
                self._up_ticks = self._down_ticks = 0
                return None
            if action == "force":
                raw = int(arg or 0)

        lo = max(1, int(self._cfg("min-parallelism", 1)))
        hi = max(lo, int(self._cfg("max-parallelism", 8)))
        target = min(hi, max(lo, raw))
        decision = {
            "direction": direction,
            "from": parallelism,
            "to": target,
            "raw_target": raw,
            "clamped": target != raw,
            "signals": [s["signal"] for s in self._last_signals
                        if s.get("breaching")],
        }
        if target == parallelism:
            # rails collapsed the decision to a no-op (already at a
            # bound): record it — once per (direction, from, to), so a
            # sustained breach at the bound cannot re-emit every window
            # just because the breaching-signal set fluctuates — and
            # never churn the worker set
            self._up_ticks = self._down_ticks = 0
            noop_key = (direction, parallelism, target)
            if noop_key != self._last_noop:
                self._last_noop = noop_key
                self._emit("INFO", "AUTOSCALE_DECISION",
                           f"decision {direction} {parallelism} -> {target} "
                           "is a no-op at the configured bounds",
                           data=decision)
            self.last_decision = decision
            return None
        self._last_noop = None
        self.last_decision = decision
        self._up_ticks = self._down_ticks = 0
        self.in_flight = target
        self._emit("INFO", "AUTOSCALE_DECISION",
                   f"scale {direction}: parallelism {parallelism} -> "
                   f"{target}" + (" (rail-clamped)" if decision["clamped"]
                                  else ""),
                   data=decision)
        return target

    def _classify(self, metrics: Optional[dict]) -> tuple[bool, bool]:
        """(pressure, headroom) for one snapshot. Pressure: ANY up-signal
        breaching. Headroom: metrics present, NO signal breaching, worst
        busy% and backpressure both under their scale-down ceilings —
        absent observations prove nothing (a brand-new set with no busy%
        yet must not look idle)."""
        self._last_signals = []
        if not metrics:
            return False, False
        pressure = False
        for sig in UP_SIGNALS:
            value = sig.observe(metrics)
            threshold = sig.threshold()
            breaching = value is not None and value >= threshold
            pressure = pressure or breaching
            self._last_signals.append({
                "signal": sig.signal_id, "value": value,
                "threshold": threshold, "breaching": breaching,
            })
        busy = _worst(metrics, "busy_pct")
        bp = _worst(metrics, "backpressure")
        busy_max = float(self._cfg("down-busy-max-pct", 25.0))
        bp_max = float(self._cfg("down-backpressure-max", 0.1))
        headroom = (not pressure and busy is not None and bp is not None
                    and busy <= busy_max and bp <= bp_max)
        # NOT a "breaching" entry — for this row true means HEALTHY
        # (idle enough to scale down), the opposite polarity of the
        # pressure signals above, so it carries its own field name
        self._last_signals.append({
            "signal": "headroom", "value": busy, "threshold": busy_max,
            "proven": headroom,
        })
        return pressure, headroom

    # ------------------------------------------------------- transitions

    def on_worker_set_started(self) -> None:
        """A worker set (re)started — fresh schedule, crash restore, or
        rescale completion. Cooldown always arms (post-restart metrics
        are warm-up noise whoever caused the restart); a cleanly landed
        autoscale additionally resets the backoff streak."""
        now = self._clock()
        self._cooldown_until = now + float(self._cfg("cooldown-s", 30.0))
        self._up_ticks = self._down_ticks = 0
        self._last_blocked = None
        if self.in_flight is not None:
            self.in_flight = None
            if not self._disrupted:
                # only a CLEAN landing resets the backoff streak — a
                # disrupted transition still reaches the new scale, but
                # its armed backoff must survive this restart
                self._failures = 0
                self._backoff_until = 0.0
        self._disrupted = False

    def on_capacity_blocked(self, parallelism: int, target: int) -> None:
        """The decided scale-up could not be placed into the fleet's
        shared capacity (controller/fleet.py ``try_grow`` refused). The
        decision is abandoned WITHOUT cooldown or disrupted-transition
        backoff — nothing happened to the worker set — and the pressure
        hysteresis is re-armed at its threshold so the decision re-fires
        on the first pressured tick after the fleet grows the pool. The
        shortfall itself was already noted as fleet pressure by try_grow;
        this records why the job did not scale."""
        self.in_flight = None
        self._disrupted = False
        self._up_ticks = max(1, int(self._cfg("up-ticks", 3)))
        key = (parallelism, target)
        if key == self._last_blocked:
            return  # the block re-fires every pressured tick; say it once
        self._last_blocked = key
        self._emit("WARN", "AUTOSCALE_DECISION",
                   f"scale up {parallelism} -> {target} blocked by fleet "
                   "capacity; fleet pressure raised, decision re-arms "
                   "once the pool grows",
                   data={"direction": "up", "from": parallelism,
                         "to": target, "blocked_by": "fleet-capacity"})

    def abandon_in_flight(self) -> None:
        """The decided scale never actuated (e.g. a manual rescale request
        won the desired_parallelism write race): forget it without arming
        cooldown or backoff — nothing happened to the worker set."""
        self.in_flight = None
        self._disrupted = False

    def on_scale_disrupted(self, reason: str) -> None:
        """The transition of an autoscaler-initiated rescale was
        disrupted (worker death mid-drain, wedged-drain escalation). The
        rescale itself still lands — the controller proceeds to the new
        parallelism from whatever checkpoint exists — but the NEXT
        decision backs off exponentially: a transition that keeps
        failing must not be retried on a tight loop."""
        if self.in_flight is None:
            return
        self._disrupted = True
        self._failures += 1
        base = float(self._cfg("backoff-base-s", 10.0))
        mult = float(self._cfg("backoff-multiplier", 2.0))
        cap = float(self._cfg("backoff-max-s", 300.0))
        delay = min(cap, base * (mult ** (self._failures - 1)))
        self._backoff_until = self._clock() + delay
        self._emit("WARN", "AUTOSCALE_BACKOFF",
                   f"scale transition disrupted ({reason.splitlines()[0][:200]}); "
                   f"next decision backed off {delay:.1f}s "
                   f"(attempt {self._failures})",
                   data={"backoff_s": delay, "failures": self._failures})

    # ----------------------------------------------------------- surface

    def target(self, parallelism: int) -> int:
        """The ``arroyo_autoscaler_target`` gauge value: the in-flight
        target while a scale actuates, else the current parallelism."""
        return self.in_flight if self.in_flight is not None else parallelism

    def detail(self, parallelism: int) -> dict:
        """The ``autoscaler`` block on /health: live rail state plus the
        last decision, so an operator can see WHY it is (not) scaling."""
        now = self._clock()
        return {
            "enabled": self.enabled(),
            "parallelism": parallelism,
            "target": self.target(parallelism),
            "in_flight": self.in_flight is not None,
            "up_ticks": self._up_ticks,
            "down_ticks": self._down_ticks,
            "cooldown_remaining_s": round(max(0.0, self._cooldown_until - now), 3),
            "backoff_remaining_s": round(max(0.0, self._backoff_until - now), 3),
            "failures": self._failures,
            "signals": self._last_signals,
            "last_decision": self.last_decision,
        }
