"""Kubernetes scheduler: one worker pod per job.

Equivalent of crates/arroyo-controller/src/schedulers/kubernetes/mod.rs
(creates worker pods from the kubernetes-scheduler.worker config and tears
them down with the job). The pod runs this framework's node daemon with one
slot; the daemon dials home to the cluster API, registers under the node id
injected into the pod, and the controller then places the worker over the
node's HTTP surface — so the in-cluster control path is identical to the
node scheduler's, and only pod lifecycle goes through the Kubernetes API.

Pod startup (image pull, scheduling) can take minutes, and the controller
loop steps every job on one thread — so ``start_worker`` only issues the
(fast) pod-create call and returns a handle that finishes placement lazily
from ``poll_events``; the supervision loop keeps servicing every other job
while the pod comes up.

The API client is a small urllib wrapper (in-cluster service-account
token + CA, or an explicit base URL for tests/kubeconfig-less setups) —
no kubernetes package needed in the air-gapped image.
"""

from __future__ import annotations

import json
import os
import ssl
import time
import urllib.error
import urllib.request
import uuid
from typing import Optional

from ..config import config
from .scheduler import NodeWorkerHandle, Scheduler, WorkerHandle

_SA = "/var/run/secrets/kubernetes.io/serviceaccount"
_TOKEN_TTL_S = 60.0  # kubelet rotates bound SA tokens; re-read periodically


class KubeClient:
    def __init__(self, base_url: Optional[str] = None, token: Optional[str] = None,
                 verify_ca: bool = True):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._static_token = token
        self._token: Optional[str] = token
        self._token_read_at: Optional[float] = None  # monotonic starts at boot
        self.ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            self.ctx = ssl.create_default_context(
                cafile=f"{_SA}/ca.crt" if os.path.exists(f"{_SA}/ca.crt") else None
            )
            if not verify_ca:
                self.ctx.check_hostname = False
                self.ctx.verify_mode = ssl.CERT_NONE

    def _bearer(self) -> Optional[str]:
        if self._static_token is not None:
            return self._static_token
        now = time.monotonic()
        if (self._token_read_at is None or now - self._token_read_at > _TOKEN_TTL_S) \
                and os.path.exists(f"{_SA}/token"):
            with open(f"{_SA}/token") as f:
                self._token = f.read().strip()
            self._token_read_at = now
        return self._token

    def _req(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        token = self._bearer()
        req = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Content-Type": "application/json",
                **({"Authorization": f"Bearer {token}"} if token else {}),
            },
        )
        with urllib.request.urlopen(req, timeout=30, context=self.ctx) as r:
            return json.loads(r.read() or b"{}")

    def create_pod(self, namespace: str, manifest: dict) -> dict:
        return self._req("POST", f"/api/v1/namespaces/{namespace}/pods", manifest)

    def delete_pod(self, namespace: str, name: str) -> None:
        try:
            self._req("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")
        except OSError:
            pass

    def pod_phase(self, namespace: str, name: str) -> str:
        try:
            pod = self._req("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")
            return pod.get("status", {}).get("phase", "Unknown")
        except OSError:
            return "Unknown"


class KubernetesWorkerHandle(WorkerHandle):
    """Pod-backed worker. Placement is lazy: the pod was just created when
    this handle is returned, and each poll_events tick tries to promote to a
    live NodeWorkerHandle once the pod's node daemon has dialed home;
    control commands issued in the window are queued and replayed."""

    def __init__(self, sched: "KubernetesScheduler", pod_name: str, node_id: str,
                 args: tuple):
        self._sched = sched
        self._pod_name = pod_name
        self._node_id = node_id
        self._args = args  # (sql, job_id, parallelism, restore_epoch, storage_url, udf_specs, graph_json)
        self._inner: Optional[NodeWorkerHandle] = None
        self._deadline = time.monotonic() + sched.startup_timeout
        self._queued: list[tuple] = []
        self._dead = False

    # ---------------------------------------------------------- placement

    def _try_place(self) -> Optional[list[dict]]:
        """Attempt promotion; returns a failure-event list when the pod is
        declared dead, else None."""
        nodes = [n for n in self._sched.db.list_nodes(alive_within_s=10.0)
                 if n["id"] == self._node_id]
        if nodes:
            try:
                self._inner = NodeWorkerHandle(nodes[0]["addr"], *self._args)
            except (urllib.error.HTTPError, OSError):
                self._inner = None  # daemon not quite ready; retry next poll
            else:
                for cmd in self._queued:
                    getattr(self._inner, cmd[0])(*cmd[1:])
                self._queued.clear()
                return None
        if time.monotonic() > self._deadline:
            phase = self._sched.kube.pod_phase(self._sched.namespace, self._pod_name)
            self.kill()
            return [{"event": "failed", "error": (
                f"worker pod {self._pod_name} never registered within "
                f"{self._sched.startup_timeout:.0f}s "
                f"(pod phase: {phase}, image: {self._sched.image})")}]
        return None

    # ------------------------------------------------------------- surface

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        if self._inner is None:
            self._queued.append(("trigger_checkpoint", epoch, then_stop))
        else:
            self._inner.trigger_checkpoint(epoch, then_stop)

    def stop(self) -> None:
        if self._inner is None:
            self._queued.append(("stop",))
        else:
            self._inner.stop()

    def kill(self) -> None:
        self._dead = True
        if self._inner is not None:
            self._inner.kill()
        self._sched.kube.delete_pod(self._sched.namespace, self._pod_name)

    def poll_events(self) -> list[dict]:
        if self._dead:
            return []
        if self._inner is None:
            return self._try_place() or []
        return self._inner.poll_events()

    def alive(self) -> bool:
        if self._dead:
            return False
        return True if self._inner is None else self._inner.alive()

    def last_heartbeat(self) -> float:
        if self._inner is None:
            return time.monotonic()  # pod startup has its own deadline
        return self._inner.last_heartbeat()


class KubernetesScheduler(Scheduler):
    """config (section kubernetes-scheduler): namespace, image,
    controller-url (the cluster API the pod dials home to), worker-env
    (extra env dict), pod-startup-timeout-s."""

    def __init__(self, db, kube: Optional[KubeClient] = None):
        self.db = db
        self.kube = kube or KubeClient()
        k = config().section("kubernetes-scheduler")
        self.namespace = k.get("namespace", "arroyo-tpu")
        self.image = k.get("image", "arroyo-tpu:latest")
        self.controller_url = k.get("controller-url", "http://arroyo-api:5115")
        self.extra_env = dict(k.get("worker-env", {}))
        self.startup_timeout = float(k.get("pod-startup-timeout-s", 120))

    def _manifest(self, pod_name: str, node_id: str) -> dict:
        env = [
            {"name": "ARROYO_TPU__NODE__ID", "value": node_id},
            {"name": "POD_IP", "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}}},
        ] + [{"name": k, "value": str(v)} for k, v in self.extra_env.items()]
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "labels": {"app": "arroyo-tpu-worker", "arroyo-node-id": node_id},
            },
            "spec": {
                "restartPolicy": "Never",
                "containers": [{
                    "name": "worker",
                    "image": self.image,
                    "args": ["node", "--controller", self.controller_url,
                             "--slots", "1", "--port", "5200",
                             "--advertise-host", "$(POD_IP)"],
                    "ports": [{"containerPort": 5200}],
                    "env": env,
                }],
            },
        }

    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None):
        node_id = f"node_{uuid.uuid4().hex[:12]}"
        pod_name = f"arroyo-worker-{job_id.replace('_', '-')[:30]}-{node_id[5:11]}"
        self.kube.create_pod(self.namespace, self._manifest(pod_name, node_id))
        return KubernetesWorkerHandle(
            self, pod_name, node_id,
            (sql, job_id, parallelism, restore_epoch, storage_url,
             udf_specs, graph_json),
        )
