"""Control plane: job lifecycle, schedulers, REST API.

TPU-native parallel of crates/arroyo-controller + arroyo-api (SURVEY §2.4):
a job state machine driving pipelines from Created through Running with
bounded restarts, periodic checkpoint triggering, worker supervision via an
embedded engine or spawned worker processes, and an axum-equivalent REST API
(http.server) over a SQLite pipeline/job store.
"""

from .db import Database
from .states import JobState
from .controller import ControllerServer, JobController

__all__ = ["Database", "JobState", "ControllerServer", "JobController"]
