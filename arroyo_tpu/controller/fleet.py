"""Multi-tenant fleet: shared capacity, admission control, fair queueing.

ROADMAP item 5. Production scale is not one big pipeline — it is hundreds
of pipelines from many tenants sharing one worker fleet. This module is
the control plane for that sharing, owned by the ControllerServer and
consulted by every JobController on its supervision tick:

* **Slot ledger** — the fleet's capacity is a pool of slots (one slot per
  parallel pipeline lane; a worker set of W processes holds at least W).
  Process/Embedded schedulers get a configurable synthetic pool
  (``fleet.slots``) so the whole feature is testable without daemons; the
  node scheduler derives capacity from registered node daemons' live
  ``/status`` slots. ``fleet.slots = 0`` (the default) means UNLIMITED:
  admission always grants and the layer is pass-through.

* **Admission control** — a job the fleet cannot place (or whose tenant
  is at quota) waits in a FIFO-per-tenant queue instead of failing.
  Dequeue is deficit round-robin across tenants: each admission round
  adds ``fleet.drr-quantum`` slot credit to a tenant with an eligible
  head-of-queue job; the head admits once its credit covers its demand
  AND free capacity exists — so a tenant streaming many small jobs
  cannot starve a tenant with a few big ones. The first credit-satisfied
  head that does NOT fit blocks further admissions (capacity
  reservation): freed slots flow to it, never around it, so big jobs
  cannot be starved by a stream of small ones either.

* **Quotas** — per-tenant ``fleet.quota.max-slots`` / ``max-jobs``
  (0 = unlimited; per-tenant overrides under ``fleet.quota.tenants.<t>``).
  A job whose own demand exceeds its tenant's max-slots is REJECTED (it
  could never run); a job that merely pushes usage past the quota QUEUES
  until a peer finishes. Lowering a quota below current usage marks the
  tenant's most recently admitted jobs for preemption: the controller
  drains each behind a checkpoint and re-queues it (JOB_PREEMPTED).

* **Requeue backoff** — a placement rejection (node-daemon 409, injected
  ``admission`` fault) re-queues the job at the HEAD of its tenant queue
  with a deterministic exponential backoff (``fleet.requeue-backoff-*``);
  it is never failed and never burns a restart-budget token.

* **Fleet elasticity** — sustained capacity-blocked queue demand (or a
  per-job autoscale the pool could not place) is fleet pressure; with
  ``fleet.autoscale.enabled`` the pool grows toward demand through the
  scheduler's ``provision_slots`` hook (synthetic pools apply the new
  size directly; cluster pools surface the target as the
  ``arroyo_fleet_target_workers`` gauge for the node-pool autoscaler).
  Same rails as the per-job loop: hysteresis, cooldown, clamped bounds.

All decisions surface as structured job events (JOB_QUEUED /
JOB_ADMITTED / JOB_REJECTED / JOB_PREEMPTED, emitted by the controller),
the ``arroyo_fleet_*`` gauges, a persisted ``fleet_state`` DB snapshot
behind ``GET /api/v1/fleet``, and queue positions on the jobs API.

The clock is injectable so unit tests drive backoff/cooldown with a fake
clock and zero sleeps.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..obs.lockorder import make_lock

_log = logging.getLogger("arroyo_tpu.controller.fleet")


def _cfg(key: str, default):
    from ..config import config

    v = config().get(f"fleet.{key}")
    return default if v is None else v


def demand_slots(n_workers: int, parallelism: int) -> int:
    """A job's slot demand: one slot per parallel pipeline lane, and at
    least one per worker process of its set."""
    return max(1, int(n_workers or 1), int(parallelism or 1))


@dataclass
class _Held:
    """One admitted job's ledger entry."""

    job_id: str
    tenant: str
    slots: int
    seq: int  # admission order; preemption picks the newest first


@dataclass
class _Queued:
    job_id: str
    tenant: str
    slots: int
    seq: int  # enqueue order (FIFO within the tenant)
    # persisted queue position carried across a controller restart, so
    # re-adopted entries restore in their original FIFO order no matter
    # which JobController happens to tick first (fresh entries: None)
    restored_pos: Optional[int] = None


@dataclass
class _Backoff:
    until: float = 0.0
    failures: int = 0


class FleetManager:
    """Slot ledger + per-tenant admission queues + the fleet autoscaler.

    One instance per ControllerServer, shared by its JobControllers. All
    methods are called from the single-threaded supervision loop; the
    lock exists so ad-hoc readers (tests, stats) stay safe.
    """

    def __init__(self, scheduler=None,
                 clock: Callable[[], float] = time.monotonic):
        self.scheduler = scheduler
        self._clock = clock
        self._lock = make_lock("FleetManager._lock", kind="rlock")
        self._held: dict[str, _Held] = {}
        self._queues: dict[str, deque[_Queued]] = {}
        self._backoff: dict[str, _Backoff] = {}
        self._grants: set[str] = set()  # admitted this pass, not yet observed
        self._preempt: set[str] = set()
        # marked-and-taken preemptions whose drain is still in flight: the
        # job holds its slots until the drain lands, but its recovery
        # already counts toward the tenant's over-quota math (and it must
        # not be re-marked every tick)
        self._preempt_inflight: set[str] = set()
        self._deficit: dict[str, int] = {}  # DRR credit per tenant
        self._seq = 0
        self._last_tenant: Optional[str] = None  # DRR rotation cursor
        # capacity-blocked demand observed by the last admission pass and
        # per-job scale-up shortfalls noted since the last tick — the
        # fleet autoscaler's pressure signals
        self._blocked_demand = 0
        self._pressure_slots = 0
        # node-scheduler capacity probe cache (live /status sums); the
        # probe itself runs on a background thread — a wedged daemon's
        # 2s-timeout HTTP call must not stall the supervision loop (the
        # exact cross-job interference the tick budget exists to prevent)
        self._node_capacity: Optional[int] = None
        self._node_probe_at = 0.0
        self._probe_thread: Optional[threading.Thread] = None
        # fleet autoscaler state
        self._dyn_pool: Optional[int] = None  # synthetic pool, resized
        self._as_up = 0
        self._as_down = 0
        self._as_cooldown_until = 0.0
        self._target: Optional[int] = None
        self._persist_at = 0.0
        self._persist_fp = None

    # ------------------------------------------------------------ capacity

    def pool_slots(self) -> Optional[int]:
        """Current pool size in slots; None = unlimited (feature off)."""
        base = int(_cfg("slots", 0) or 0)
        with self._lock:  # _dyn_pool / _node_capacity land on other threads
            if base > 0:
                if self._dyn_pool is not None:
                    return max(base, self._dyn_pool)
                return base
            return self._node_capacity  # None until a node probe lands

    def _achievable_pool(self) -> float:
        """The largest pool this fleet could ever offer a single job:
        the current pool, or the autoscaler's max-slots ceiling when
        fleet elasticity could grow it. Demands beyond this can never be
        placed and must not hold the admission pass hostage."""
        pool = self.pool_slots()
        if pool is None:
            return float("inf")
        if bool(_cfg("autoscale.enabled", False)):
            return max(pool, int(_cfg("autoscale.max-slots", 64)))
        return pool

    def used_slots(self) -> int:
        with self._lock:
            return sum(e.slots for e in self._held.values())

    def free_slots(self) -> Optional[int]:
        pool = self.pool_slots()
        if pool is None:
            return None
        return max(0, pool - self.used_slots())

    def _refresh_node_capacity(self, db) -> None:
        """Node scheduler only: fleet capacity is the live sum of
        registered daemons' slots (each worker process = one slot there;
        the daemon's own 409 stays the physical backstop). Throttled AND
        backgrounded: a wedged daemon's blocking /status probe must never
        stall the supervision tick — the pass uses the last cached sum
        until the probe thread lands a fresh one."""
        from .scheduler import NodeScheduler

        if not isinstance(self.scheduler, NodeScheduler) or db is None:
            return
        now = self._clock()
        if now - self._node_probe_at < 2.0:
            return
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return  # previous probe still running; cache stays in force
        self._node_probe_at = now
        nodes = db.list_nodes(alive_within_s=10.0)  # cheap local DB read

        def _probe() -> None:
            from .node import _get

            total = 0
            for n in nodes:
                try:
                    st = _get(f"{n['addr']}/status", timeout=2.0)
                    total += int(st["slots"])
                except (OSError, KeyError, ValueError):
                    # unreachable daemon: fall back to its registered
                    # slots — placement itself discovers the truth
                    # (409 -> requeue)
                    total += int(n.get("slots") or 0)
            with self._lock:  # published to pool_slots() readers
                self._node_capacity = total if nodes else None

        self._probe_thread = threading.Thread(
            target=_probe, daemon=True, name="fleet-node-probe")
        self._probe_thread.start()

    # -------------------------------------------------------------- quotas

    @staticmethod
    def _quota(tenant: str, which: str) -> int:
        from ..config import config

        v = config().get(f"fleet.quota.tenants.{tenant}.{which}")
        if v is None:
            v = config().get(f"fleet.quota.{which}")
        return int(v or 0)

    def tenant_usage(self, tenant: str) -> tuple[int, int]:
        """(slots in use, jobs admitted) for one tenant."""
        with self._lock:
            rows = [e for e in self._held.values() if e.tenant == tenant]
        return sum(e.slots for e in rows), len(rows)

    def _quota_allows(self, tenant: str, slots: int) -> bool:
        used, jobs = self.tenant_usage(tenant)
        max_slots = self._quota(tenant, "max-slots")
        max_jobs = self._quota(tenant, "max-jobs")
        if max_slots and used + slots > max_slots:
            return False
        if max_jobs and jobs + 1 > max_jobs:
            return False
        return True

    # ----------------------------------------------------------- admission

    def admit(self, job_id: str, tenant: str, slots: int) -> tuple[str, str]:
        """Request admission. Returns (verdict, reason) with verdict one of
        ``admitted`` / ``queued`` / ``rejected``. The job is enqueued and a
        DRR pass runs, so a newcomer can never jump ahead of queued peers."""
        with self._lock:
            if job_id in self._held:
                return "admitted", "already holds slots"
            max_slots = self._quota(tenant, "max-slots")
            if max_slots and slots > max_slots:
                return "rejected", (
                    f"demand {slots} slots exceeds tenant {tenant!r} quota "
                    f"max-slots={max_slots}: the job could never run")
            self._enqueue(job_id, tenant, slots, front=False)
            self._run_admissions()
            if job_id in self._grants:
                self._grants.discard(job_id)
                return "admitted", "placed into shared capacity"
            return "queued", self._queue_reason(tenant, slots)

    def _queue_reason(self, tenant: str, slots: int) -> str:
        if not self._quota_allows(tenant, slots):
            return f"tenant {tenant!r} at quota"
        free = self.free_slots()
        return (f"fleet full ({free} of {self.pool_slots()} slots free, "
                f"need {slots})")

    def _enqueue(self, job_id: str, tenant: str, slots: int,
                 front: bool) -> None:
        q = self._queues.setdefault(tenant, deque())
        if any(e.job_id == job_id for e in q):
            return
        self._seq += 1
        entry = _Queued(job_id, tenant, slots, self._seq)
        if front:
            q.appendleft(entry)
        else:
            q.append(entry)

    def should_admit(self, job_id: str) -> bool:
        """True exactly once after an admission pass granted the job; the
        QUEUED JobController consumes this to transition to Scheduling."""
        with self._lock:
            if job_id in self._grants:
                self._grants.discard(job_id)
                return True
            return False

    def holds(self, job_id: str) -> bool:
        with self._lock:
            return job_id in self._held

    def adopt(self, job_id: str, tenant: str, slots: int) -> None:
        """Force-register usage for a job a fresh controller adopted
        mid-flight (controller restart): the job is already running, so
        the ledger must reflect it even if that oversubscribes the pool
        (free clamps at zero; pressure drains it over time)."""
        with self._lock:
            if job_id not in self._held:
                self._seq += 1
                self._held[job_id] = _Held(job_id, tenant, slots, self._seq)

    def release(self, job_id: str) -> None:
        """The job went terminal (or its queue entry was cancelled): free
        its slots / queue position. Freed capacity is handed out by the
        next supervision tick's admission pass."""
        with self._lock:
            self._held.pop(job_id, None)
            self._grants.discard(job_id)
            self._preempt.discard(job_id)
            self._preempt_inflight.discard(job_id)
            self._backoff.pop(job_id, None)
            for q in self._queues.values():
                for e in list(q):
                    if e.job_id == job_id:
                        q.remove(e)

    def requeue(self, job_id: str, tenant: str, slots: int,
                backoff: bool = False) -> None:
        """Move an admitted (or granted) job back to the HEAD of its
        tenant queue — placement was rejected (node 409) or the job is
        being preempted. ``backoff`` arms the deterministic exponential
        ineligibility window; a preemption re-queues without one."""
        with self._lock:
            self._held.pop(job_id, None)
            self._grants.discard(job_id)
            self._preempt.discard(job_id)
            self._preempt_inflight.discard(job_id)
            self._enqueue(job_id, tenant, slots, front=True)
            if backoff:
                b = self._backoff.setdefault(job_id, _Backoff())
                b.failures += 1
                base = float(_cfg("requeue-backoff-base-s", 0.5))
                cap = float(_cfg("requeue-backoff-max-s", 30.0))
                delay = min(cap, base * (2.0 ** (b.failures - 1)))
                b.until = self._clock() + delay
            else:
                self._backoff.pop(job_id, None)

    def restore_queued(self, job_id: str, tenant: str, slots: int,
                       position: Optional[int] = None) -> None:
        """Re-adopt a Queued job after a controller restart, preserving
        the PERSISTED queue order: adoption happens per-JobController in
        arbitrary tick order, so each entry carries its old position and
        inserts sorted — ahead of fresh (position-less) entries."""
        with self._lock:
            if job_id in self._held:
                return
            q = self._queues.setdefault(tenant, deque())
            if any(e.job_id == job_id for e in q):
                return
            self._seq += 1
            entry = _Queued(job_id, tenant, slots, self._seq,
                            restored_pos=position)
            if position is None:
                q.append(entry)
                return
            idx = len(q)
            for i, e in enumerate(q):
                if e.restored_pos is None or e.restored_pos > position:
                    idx = i
                    break
            q.insert(idx, entry)

    def clear_backoff(self, job_id: str) -> None:
        """A placement finally landed: the consecutive-rejection streak
        resets so the next (unrelated) requeue starts from the base."""
        with self._lock:
            self._backoff.pop(job_id, None)

    def backoff_remaining(self, job_id: str) -> float:
        with self._lock:
            b = self._backoff.get(job_id)
        return max(0.0, b.until - self._clock()) if b else 0.0

    def _run_admissions(self) -> None:
        """One deficit-round-robin pass (lock held): grant queued jobs
        into free capacity. Grants move straight into the ledger (so
        capacity accounting is correct before the job's own tick) and are
        surfaced once via ``should_admit``."""
        self._blocked_demand = 0
        pool = self.pool_slots()
        free = None if pool is None else max(0, pool - sum(
            e.slots for e in self._held.values()))
        deficit = self._deficit
        quantum = max(1, int(_cfg("drr-quantum", 1)))
        now = self._clock()
        # in-pass capacity reservations: a head that FITS the pool but is
        # still accruing credit pins its demand, so smaller jobs of other
        # tenants cannot drain the capacity out from under it while its
        # deficit counter catches up (at quantum 1 a 3-slot job needs 3
        # rounds — all inside this one pass)
        pending: dict[str, int] = {}
        progress = True
        rounds = 0
        while progress and rounds < 1024:  # bound is a safety net only
            rounds += 1
            progress = False
            tenants = sorted(t for t, q in self._queues.items() if q)
            if not tenants:
                break
            # rotation: resume after the last tenant served
            if self._last_tenant in tenants:
                i = tenants.index(self._last_tenant) + 1
                tenants = tenants[i:] + tenants[:i]
            for tenant in tenants:
                q = self._queues.get(tenant)
                if not q:
                    deficit.pop(tenant, None)
                    continue
                head = q[0]
                b = self._backoff.get(head.job_id)
                if b is not None and now < b.until:
                    continue  # rejected recently; ineligible, no credit
                if not self._quota_allows(tenant, head.slots):
                    continue  # tenant at quota; its whole queue waits
                # chaos site `fleet_place` (ctx: key=job, tenant, slots):
                # drop suppresses this head's placement decision for the
                # pass; force grants it regardless of credit or capacity
                # (the ledger absorbs the oversubscription as pressure)
                from ..faults import InjectedFault, fault_point

                forced = False
                try:
                    verdict = fault_point("fleet_place", key=head.job_id,
                                          tenant=tenant, slots=head.slots)
                except InjectedFault:
                    continue  # decision computation "failed": costs a pass
                if verdict is not None:
                    if verdict[0] == "drop":
                        continue
                    forced = verdict[0] == "force"
                reserved = sum(v for k, v in pending.items()
                               if k != head.job_id)
                if not forced and free is not None:
                    if head.slots > self._achievable_pool():
                        # this head could NEVER fit — not even a fully
                        # drained (or autoscaled-to-max) pool holds it.
                        # It stays Queued (never Failed), but it must not
                        # reserve capacity — that would starve every other
                        # tenant's queue behind an impossible demand —
                        # and it adds no autoscale pressure (no amount of
                        # growth would place it).
                        continue
                    if head.slots > free:
                        # TRUE capacity shortage: the next eligible head
                        # that cannot fit the pool's free slots blocks
                        # the whole pass — freed slots flow to IT, never
                        # around it (anti-starvation for big jobs behind
                        # streams of small ones). Everything still queued
                        # behind a non-quota-blocked head is capacity-
                        # blocked demand: the autoscaler's pressure.
                        self._blocked_demand += sum(
                            e.slots for t2, q2 in self._queues.items()
                            if q2 and self._quota_allows(t2, q2[0].slots)
                            for e in q2)
                        return
                    if head.slots > free - reserved:
                        # the shortage is another head's in-pass
                        # reservation, not real scarcity: skip the round
                        continue
                deficit[tenant] = deficit.get(tenant, 0) + quantum
                if not forced and deficit[tenant] < head.slots:
                    # credit accrues across ROUNDS (the job fits — more
                    # rounds this pass will satisfy it), so a multi-slot
                    # job admits within one tick once capacity exists;
                    # its demand is pinned meanwhile (see `pending`)
                    pending[head.job_id] = head.slots
                    progress = True
                    continue
                q.popleft()
                pending.pop(head.job_id, None)
                deficit[tenant] = max(0, deficit.get(tenant, 0) - head.slots)
                if free is not None:
                    free -= head.slots
                self._seq += 1
                self._held[head.job_id] = _Held(
                    head.job_id, tenant, head.slots, self._seq)
                self._grants.add(head.job_id)
                self._last_tenant = tenant
                progress = True
        # credit does not outlive an empty queue
        for t in list(deficit):
            if not self._queues.get(t):
                deficit.pop(t, None)

    # -------------------------------------------------- demand transitions

    def try_grow(self, job_id: str, new_slots: int) -> bool:
        """Reserve extra slots for a per-job scale-up BEFORE it actuates.
        Returns False (and notes fleet pressure) when the pool cannot
        place it — the autoscale decision is skipped this round and the
        fleet loop grows the pool instead."""
        with self._lock:
            e = self._held.get(job_id)
            if e is None:
                return True  # not under fleet management
            extra = int(new_slots) - e.slots
            if extra <= 0:
                e.slots = int(new_slots)
                return True
            free = self.free_slots()
            if free is None or extra <= free:
                e.slots = int(new_slots)
                return True
            self._pressure_slots += extra - free
            return False

    def set_demand(self, job_id: str, new_slots: int) -> None:
        """Unconditional ledger update (manual rescales always win, even
        if that oversubscribes the pool — free clamps at zero and the
        overdraft reads as fleet pressure)."""
        with self._lock:
            e = self._held.get(job_id)
            if e is None:
                return
            pool = self.pool_slots()
            e.slots = int(new_slots)
            if pool is not None:
                over = sum(x.slots for x in self._held.values()) - pool
                if over > 0:
                    self._pressure_slots += over

    def note_pressure(self, slots_short: int) -> None:
        with self._lock:
            self._pressure_slots += max(0, int(slots_short))

    # ----------------------------------------------------------- preemption

    def take_preemption(self, job_id: str) -> bool:
        """True once when the fleet marked this job for preemption (its
        tenant's quota dropped below current usage); the controller drains
        it behind a checkpoint and re-queues it."""
        with self._lock:
            if job_id in self._preempt:
                self._preempt.discard(job_id)
                return True
            return False

    def _mark_preemptions(self) -> None:
        with self._lock:
            by_tenant: dict[str, list[_Held]] = {}
            for e in self._held.values():
                by_tenant.setdefault(e.tenant, []).append(e)
            for tenant, rows in by_tenant.items():
                max_slots = self._quota(tenant, "max-slots")
                if not max_slots:
                    continue
                over = sum(e.slots for e in rows) - max_slots
                if over <= 0:
                    continue
                # newest admissions yield first; jobs already marked (or
                # mid-drain) count toward the recovery in flight
                for e in sorted(rows, key=lambda x: -x.seq):
                    if over <= 0:
                        break
                    if e.job_id not in self._preempt \
                            and e.job_id not in self._preempt_inflight:
                        self._preempt.add(e.job_id)
                        self._preempt_inflight.add(e.job_id)
                        _log.warning(
                            "tenant %r over quota (%d > %d slots): "
                            "preempting %s", tenant,
                            sum(x.slots for x in rows), max_slots, e.job_id)
                    over -= e.slots

    # ------------------------------------------------------ queue surfaces

    def queue_order(self) -> list[_Queued]:
        """Queued jobs in (approximate) admission order: tenants
        interleaved round-robin, FIFO within each — what queue positions
        are derived from."""
        with self._lock:
            qs = {t: list(q) for t, q in self._queues.items() if q}
        out: list[_Queued] = []
        i = 0
        while any(qs.values()):
            for t in sorted(qs):
                if i < len(qs[t]):
                    out.append(qs[t][i])
            i += 1
            if i > max((len(v) for v in qs.values()), default=0):
                break
        return out

    def queue_position(self, job_id: str) -> Optional[int]:
        for i, e in enumerate(self.queue_order()):
            if e.job_id == job_id:
                return i + 1
        return None

    def queue_depth(self) -> dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items() if q}

    def stats(self) -> dict:
        """The fleet snapshot behind the gauges, ``GET /api/v1/fleet``,
        and the persisted fleet_state row."""
        with self._lock:
            held = list(self._held.values())
            order = self.queue_order()
        tenants: dict[str, dict] = {}
        for e in held:
            t = tenants.setdefault(e.tenant, {"slots_used": 0,
                                              "jobs_running": 0,
                                              "queued": 0})
            t["slots_used"] += e.slots
            t["jobs_running"] += 1
        for e in order:
            t = tenants.setdefault(e.tenant, {"slots_used": 0,
                                              "jobs_running": 0,
                                              "queued": 0})
            t["queued"] += 1
        pool = self.pool_slots()
        used = sum(e.slots for e in held)
        return {
            "pool_slots": pool,
            "slots_used": used,
            "slots_free": None if pool is None else max(0, pool - used),
            "target_workers": self._target if self._target is not None
            else (pool if pool is not None else used),
            "queue_depth": {t: sum(1 for e in order if e.tenant == t)
                            for t in {e.tenant for e in order}},
            "queue": [{"job_id": e.job_id, "tenant": e.tenant,
                       "slots": e.slots, "position": i + 1}
                      for i, e in enumerate(order)],
            "tenants": tenants,
        }

    # ----------------------------------------------------------- fleet tick

    def tick(self, db=None) -> None:
        """Once per ControllerServer tick, BEFORE job steps: refresh
        capacity, mark quota preemptions, run the admission pass over
        whatever capacity terminal jobs just freed, evaluate the fleet
        autoscaler, export gauges, and persist the snapshot."""
        self._refresh_node_capacity(db)
        self._mark_preemptions()
        with self._lock:
            self._run_admissions()
            blocked = self._blocked_demand
            pressure_slots = self._pressure_slots
            self._pressure_slots = 0
        self._autoscale(blocked + pressure_slots)
        stats = self.stats()
        from ..metrics import registry as metrics_registry

        metrics_registry.set_fleet_stats(stats)
        self._persist(db, stats)

    def _persist(self, db, stats: dict) -> None:
        if db is None:
            return
        now = self._clock()
        fp = (stats["slots_used"], stats["pool_slots"],
              tuple(sorted((e["job_id"], e["position"])
                           for e in stats["queue"])))
        if fp == self._persist_fp and now - self._persist_at < 1.0:
            return
        self._persist_fp = fp
        self._persist_at = now
        try:
            db.record_fleet_state(stats)
        except Exception:  # noqa: BLE001 - snapshot durability is best-effort
            _log.exception("fleet-state persist failed; retrying next tick")

    def _autoscale(self, shortfall: int) -> None:
        """Fleet-level elasticity over the same rails as the per-job
        loop: hysteresis (up/down tick streaks), cooldown after a resize,
        clamped bounds. Actuation goes through the scheduler's provision
        hook; a scheduler that returns None sizes its pool externally and
        the decision only moves the ``arroyo_fleet_target_workers``
        gauge — the knob a node-pool autoscaler keys off."""
        pool = self.pool_slots()
        if not bool(_cfg("autoscale.enabled", False)) or pool is None:
            self._target = pool
            self._as_up = self._as_down = 0
            return
        base = int(_cfg("slots", 0) or 0) or pool
        hi = max(base, int(_cfg("autoscale.max-slots", 64)))
        headroom = int(_cfg("autoscale.headroom-slots", 0) or 0)
        used = self.used_slots()
        if shortfall > 0:
            self._as_up += 1
            self._as_down = 0
        elif pool - used > headroom and not self.queue_depth():
            self._as_down += 1
            self._as_up = 0
        else:
            self._as_up = self._as_down = 0
        now = self._clock()
        target = self._target if self._target is not None else pool
        decided: Optional[int] = None
        if self._as_up >= max(1, int(_cfg("autoscale.up-ticks", 3))) \
                and now >= self._as_cooldown_until:
            decided = min(hi, max(pool, used + shortfall + headroom))
            self._as_up = 0
        elif self._as_down >= max(1, int(_cfg("autoscale.down-ticks", 20))) \
                and now >= self._as_cooldown_until:
            decided = max(base, used + headroom)
            self._as_down = 0
        # actuate (and arm the cooldown) only when a FRESH decision moves
        # the target: for an externally sized pool (provision hook returns
        # None, the pool itself never changes here) a standing target must
        # not re-enter this branch every tick — that would re-arm the
        # cooldown forever and freeze the gauge at its first value
        if decided is not None and decided != target:
            accepted = None
            if self.scheduler is not None:
                accepted = self.scheduler.provision_slots(decided)
            if accepted is not None:
                with self._lock:
                    self._dyn_pool = max(base, int(accepted))
                _log.info("fleet pool resized %d -> %d slots "
                          "(shortfall %d)", pool, self._dyn_pool, shortfall)
            else:
                _log.info("fleet target %d slots (pool %d is externally "
                          "sized; arroyo_fleet_target_workers carries the "
                          "knob)", decided, pool)
            self._as_cooldown_until = now + float(
                _cfg("autoscale.cooldown-s", 15.0))
            target = decided
        self._target = target
