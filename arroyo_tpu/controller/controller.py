"""Job supervision: the controller loop.

Reference: ControllerServer (arroyo-controller/src/lib.rs:189) polling the DB
for jobs (start_updater, lib.rs:543-567) and JobController
(job_controller/mod.rs:555) driving heartbeat timeout checks, periodic
checkpoints, failure detection, and the restart budget
(pipeline.allowed-restarts, healthy-duration resets).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Optional

from ..config import config
from ..state.tables import latest_complete_checkpoint
from .db import Database
from .scheduler import Scheduler, WorkerHandle, scheduler_for
from .states import JobState, check_transition


class JobController:
    """Supervises one job end-to-end (FSM + running-worker control)."""

    def __init__(self, db: Database, job_id: str, scheduler: Scheduler,
                 storage_url: Optional[str] = None):
        self.db = db
        self.job_id = job_id
        self.scheduler = scheduler
        self.storage_url = storage_url or config().get("checkpoint.storage-url")
        self.state = JobState(self.db.get_job(job_id)["state"])
        self.handle: Optional[WorkerHandle] = None
        self.sql: Optional[str] = None
        self.parallelism = 1
        self.restarts = 0
        self.restore_epoch: Optional[int] = None
        self.next_epoch = 1
        self.last_checkpoint_time = time.monotonic()
        self.running_since: Optional[float] = None
        self.stopping_epoch: Optional[int] = None
        self.rescale_to: Optional[int] = None
        self.failure: Optional[str] = None
        from ..metrics import RateTracker

        self.rates = RateTracker(window_s=10.0)

    # ------------------------------------------------------------------

    def _set_state(self, nxt: JobState, **fields) -> None:
        check_transition(self.state, nxt)
        self.state = nxt
        self.db.update_job(self.job_id, state=nxt.value, **fields)

    def is_terminal(self) -> bool:
        return self.state in (JobState.FAILED, JobState.FINISHED, JobState.STOPPED)

    # ------------------------------------------------------------------

    def step(self) -> None:
        """One supervision tick; cheap and non-blocking."""
        try:
            self._step_inner()
        except Exception:  # noqa: BLE001 - job failure, not controller crash
            self.failure = traceback.format_exc()
            self._fail(self.failure)

    def _fail(self, msg: str) -> None:
        if self.handle:
            self.handle.kill()
            self.handle = None
        if not self.is_terminal():
            self._set_state(JobState.FAILED, failure_message=msg[-4000:])

    def _step_inner(self) -> None:
        job = self.db.get_job(self.job_id)
        if job is None:
            self._fail("job row deleted")
            return
        desired_stop = job["desired_stop"]

        if self.state == JobState.CREATED:
            self._set_state(JobState.COMPILING)
        elif self.state == JobState.COMPILING:
            self._compile(job)
        elif self.state == JobState.SCHEDULING:
            self._schedule(job)
        elif self.state in (JobState.RUNNING, JobState.CHECKPOINT_STOPPING,
                            JobState.STOPPING, JobState.FINISHING):
            self._supervise(desired_stop, job)
        elif self.state == JobState.RESCALING:
            # the old worker is draining behind a final checkpoint; keep
            # supervising it — _supervise's finished/failed handlers do the
            # actual Rescaling -> Scheduling hop (reference rescaling.rs:16)
            if self.handle is not None:
                self._supervise(desired_stop, job)
            else:
                # adopted mid-rescale by a fresh controller: treat like a
                # restart at the (already persisted) new parallelism
                self._finish_rescale(job)
        elif self.state in (JobState.RECOVERING, JobState.RESTARTING):
            restarts_allowed = config().get("pipeline.allowed-restarts")
            if self.state == JobState.RECOVERING and self.restarts > restarts_allowed:
                self._fail(f"exceeded allowed-restarts={restarts_allowed}: {self.failure}")
                return
            self.restore_epoch = latest_complete_checkpoint(self.storage_url, self.job_id)
            self._set_state(JobState.SCHEDULING, restarts=self.restarts,
                            restore_epoch=self.restore_epoch)

    def _finish_rescale(self, job: dict) -> None:
        """Old worker is gone; restore from the freshest checkpoint at the
        new parallelism (the state layer rescales via key-range-overlap
        file reads on restore)."""
        # re-read the request: the API may have accepted a NEWER target
        # after this drain was triggered — honor the freshest value
        fresh = self.db.get_job(self.job_id) or job
        target = fresh.get("desired_parallelism") or self.rescale_to
        self.rescale_to = None
        if target:
            self.parallelism = int(target)
            self.db.set_pipeline_parallelism(job["pipeline_id"], int(target))
            # conditional clear: a request racing in after the re-read
            # above survives and triggers a follow-up rescale
            self.db.clear_desired_parallelism(self.job_id, int(target))
        self.restore_epoch = latest_complete_checkpoint(self.storage_url, self.job_id)
        self._set_state(JobState.SCHEDULING, restore_epoch=self.restore_epoch,
                        restarts=self.restarts)

    # ------------------------------------------------------------------

    def _compile(self, job: dict) -> None:
        from ..sql import plan_query

        pipeline = self.db.get_pipeline(job["pipeline_id"])
        if pipeline is None:
            self._fail("pipeline deleted")
            return
        self.sql = pipeline["query"]
        self.parallelism = int(pipeline["parallelism"])
        # a rescale accepted before the job ever ran starts the worker at
        # the new scale directly — no wasted drain cycle after Running
        want = job.get("desired_parallelism")
        if want:
            self.parallelism = int(want)
            self.db.set_pipeline_parallelism(job["pipeline_id"], int(want))
            self.db.clear_desired_parallelism(self.job_id, int(want))
        # validate with registered connection tables in scope; workers get
        # the planned IR (graph_json) so they need no DB access
        plan_query(self.sql, connection_tables=self.db.list_connection_tables())
        self._set_state(JobState.SCHEDULING)

    def _compile_graph(self):
        """Plan once in the control plane and ship the dataflow IR to
        workers as data (reference: the API compiles SQL to a protobuf
        ArrowProgram and StartExecutionReq carries it — workers never
        re-plan). Falls back to shipping SQL when a config carries live
        objects the IR cannot serialize (e.g. in-process lookup tables)."""
        try:
            from ..sql import plan_query
            from ..sql.planner import set_parallelism

            pp = plan_query(self.sql,
                            connection_tables=self.db.list_connection_tables())
            if self.parallelism > 1:
                set_parallelism(pp.graph, self.parallelism)
            dumped = pp.graph.dumps()
            from ..graph import Graph

            Graph.loads(dumped)  # round-trip check before shipping
            return dumped
        except Exception:
            return None

    def _schedule(self, job: dict) -> None:
        if self.sql is None:
            # a fresh JobController adopting a Restarting/Recovering job
            # (reference: Restarting passes back through Compiling)
            pipeline = self.db.get_pipeline(job["pipeline_id"])
            if pipeline is None:
                self._fail("pipeline deleted")
                return
            self.sql = pipeline["query"]
            self.parallelism = int(pipeline["parallelism"])
            self.restarts = int(job["restarts"])
        self.handle = self.scheduler.start_worker(
            self.sql, self.job_id, self.parallelism, self.restore_epoch,
            self.storage_url, udf_specs=self.db.list_udfs(),
            graph_json=self._compile_graph(),
        )
        self.running_since = time.monotonic()
        self.last_checkpoint_time = time.monotonic()
        if self.restore_epoch:
            self.next_epoch = self.restore_epoch + 1
        self._set_state(JobState.RUNNING)

    def _supervise(self, desired_stop: Optional[str], job: dict) -> None:
        assert self.handle is not None
        cfgv = config()
        # healthy-duration resets the restart budget (default.toml:8 analog)
        healthy_ms = cfgv.get("pipeline.healthy-duration-ms")
        if (self.restarts and self.running_since is not None
                and (time.monotonic() - self.running_since) * 1000 >= healthy_ms):
            self.restarts = 0
            self.db.update_job(self.job_id, restarts=0)

        for ev in self.handle.poll_events():
            kind = ev.get("event")
            if kind == "sink_data":
                self.db.record_output(self.job_id, ev.get("lines", []))
            elif kind == "metrics":
                data = ev.get("data") or {}
                now = time.monotonic()
                for op, m in data.items():
                    self.rates.observe(
                        f"{op}.sent", int(m.get("arroyo_worker_messages_sent", 0)), now
                    )
                    m["messages_per_sec"] = round(self.rates.rate(f"{op}.sent"), 2)
                if data:
                    self.db.record_metrics(self.job_id, data)
            elif kind == "checkpoint_completed":
                epoch = int(ev["epoch"])
                self.db.record_checkpoint(self.job_id, epoch, "complete")
                self.db.update_job(self.job_id, checkpoint_epoch=epoch)
                if self.state == JobState.CHECKPOINT_STOPPING and epoch == self.stopping_epoch:
                    self._set_state(JobState.STOPPING)
            elif kind == "finished":
                if self.state == JobState.RESCALING:
                    try:
                        self.handle.kill()
                    except Exception:  # lint: waive LR102 — best-effort kill of an already-exited worker; no recovery possible
                        pass
                    self.handle = None
                    self._finish_rescale(job)
                    return
                if self.state == JobState.STOPPING or self.state == JobState.CHECKPOINT_STOPPING:
                    self._set_state(JobState.STOPPED)
                else:
                    self._set_state(JobState.FINISHING)
                    self._set_state(JobState.FINISHED)
                # release the exited worker's resources (temp sql/udf files,
                # pipes); for a finished process this is pure cleanup
                try:
                    self.handle.kill()
                except Exception:  # lint: waive LR102 — best-effort kill during finished-worker cleanup; process is already gone
                    pass
                self.handle = None
                return
            elif kind == "failed":
                self.failure = ev.get("error", "unknown worker failure")
                self.handle.kill()
                self.handle = None
                self.restarts += 1
                if self.state == JobState.RESCALING:
                    # drain failed mid-rescale: still proceed to the new
                    # parallelism from whatever checkpoint exists
                    self._finish_rescale(job)
                elif self.state in (JobState.STOPPING, JobState.CHECKPOINT_STOPPING):
                    self._set_state(JobState.STOPPED)
                else:
                    self._set_state(JobState.RECOVERING,
                                    failure_message=self.failure[-4000:])
                return

        # heartbeat / liveness (reference worker-heartbeat-timeout)
        hb_timeout = cfgv.get("pipeline.worker-heartbeat-timeout-ms") / 1000
        if not self.handle.alive() or (
            time.monotonic() - self.handle.last_heartbeat() > hb_timeout
        ):
            self.failure = "worker lost (heartbeat timeout)"
            self.handle.kill()
            self.handle = None
            self.restarts += 1
            if self.state == JobState.RESCALING:
                # old worker died draining: rescale from the last checkpoint
                self._finish_rescale(job)
            else:
                self._set_state(JobState.RECOVERING, failure_message=self.failure)
            return

        # rescale requests from the API (reference states/rescaling.rs:1-70):
        # checkpoint-and-stop the old worker, then reschedule at the new
        # parallelism restoring from that final checkpoint
        if self.state == JobState.RUNNING and not desired_stop:
            want = job.get("desired_parallelism")
            if want and int(want) != self.parallelism:
                self.rescale_to = int(want)
                self.stopping_epoch = self.next_epoch
                self.next_epoch += 1
                self.handle.trigger_checkpoint(self.stopping_epoch, then_stop=True)
                self._set_state(JobState.RESCALING)
                return
            if want and int(want) == self.parallelism:
                # no-op rescale: clear the request
                self.db.update_job(self.job_id, desired_parallelism=None)

        # stop requests from the API; a stop also voids any pending rescale
        # so it cannot resurrect as a surprise drain cycle at a later restart
        if self.state == JobState.RUNNING and desired_stop:
            if desired_stop == "checkpoint":
                self.stopping_epoch = self.next_epoch
                self.next_epoch += 1
                self.handle.trigger_checkpoint(self.stopping_epoch, then_stop=True)
                self._set_state(JobState.CHECKPOINT_STOPPING, desired_parallelism=None)
            else:
                self.handle.stop()
                self._set_state(JobState.STOPPING, desired_parallelism=None)
            return

        # periodic checkpoints (reference default-checkpoint-interval)
        if self.state == JobState.RUNNING:
            interval = cfgv.get("checkpoint.interval-ms") / 1000
            if time.monotonic() - self.last_checkpoint_time >= interval:
                self.handle.trigger_checkpoint(self.next_epoch)
                self.next_epoch += 1
                self.last_checkpoint_time = time.monotonic()


class ControllerServer:
    """Polls the DB and supervises every live job
    (reference ControllerServer + start_updater)."""

    def __init__(self, db: Database, scheduler: Optional[Scheduler] = None,
                 storage_url: Optional[str] = None, poll_interval: float = 0.1):
        self.db = db
        self.scheduler = scheduler or scheduler_for(
            config().get("controller.scheduler"), db)
        self.storage_url = storage_url
        self.poll_interval = poll_interval
        self.jobs: dict[str, JobController] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ControllerServer":
        self._thread = threading.Thread(target=self._run, daemon=True, name="controller")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.poll_interval)

    def tick(self) -> None:
        for row in self.db.list_jobs():
            jid = row["id"]
            if jid not in self.jobs:
                if row["state"] in ("Failed", "Finished", "Stopped"):
                    continue
                self.jobs[jid] = JobController(
                    self.db, jid, self.scheduler, self.storage_url
                )
        for jid, jc in list(self.jobs.items()):
            if jc.is_terminal():
                # persist a final snapshot, then free the process-global
                # registry (it would otherwise grow per finished job)
                from ..metrics import registry as metrics_registry

                final = metrics_registry.job_metrics(jid)
                if final:
                    self.db.record_metrics(jid, final)
                metrics_registry.clear_job(jid)
                del self.jobs[jid]
                continue
            jc.step()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        for jc in self.jobs.values():
            if jc.handle:
                jc.handle.kill()

    def wait_for_state(self, job_id: str, *states: str, timeout: float = 120) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.db.get_job(job_id)
            if job and job["state"] in states:
                return job["state"]
            if job and job["state"] == "Failed" and "Failed" not in states:
                raise RuntimeError(f"job failed: {job['failure_message']}")
            time.sleep(0.05)
        raise TimeoutError(f"job {job_id} never reached {states}")
