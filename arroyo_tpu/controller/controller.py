"""Job supervision: the controller loop.

Reference: ControllerServer (arroyo-controller/src/lib.rs:189) polling the DB
for jobs (start_updater, lib.rs:543-567) and JobController
(job_controller/mod.rs:555) driving heartbeat timeout checks, periodic
checkpoints, failure detection, and the restart budget
(pipeline.allowed-restarts, healthy-duration resets).

A job runs on a WORKER SET of ``controller.workers-per-job`` workers
(start_workers; one by default). For multi-worker sets the controller also
owns cross-worker checkpoint coordination (checkpoint_state.py): per-subtask
acks flow up from every worker, the epoch goes globally durable here, and
phase-2 commits fan back out. Any worker of the set dying, missing
heartbeats, or wedging a checkpoint past ``checkpoint.timeout-ms`` (K
consecutive times) takes the WHOLE set down and restores it from the last
globally complete checkpoint.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Optional

from ..config import config
from ..obs import trace as obs_trace
from ..obs.events import recorder as events_recorder
from ..obs.health import HealthMonitor, health_event_code
from .autoscaler import Autoscaler
from .db import Database
from .fleet import FleetManager, demand_slots
from .scheduler import PlacementFull, Scheduler, WorkerHandle, scheduler_for
from .states import JobState, check_transition

_log = logging.getLogger("arroyo_tpu.controller")


class JobController:
    """Supervises one job end-to-end (FSM + running-worker-set control)."""

    def __init__(self, db: Database, job_id: str, scheduler: Scheduler,
                 storage_url: Optional[str] = None,
                 fleet: Optional[FleetManager] = None):
        self.db = db
        self.job_id = job_id
        self.scheduler = scheduler
        self.storage_url = storage_url or config().get("checkpoint.storage-url")
        job_row = self.db.get_job(job_id)
        self.state = JobState(job_row["state"])
        # multi-tenant fleet (controller/fleet.py): the shared slot pool /
        # admission queue; a standalone JobController gets its own
        # (unlimited by default, so the layer is pass-through)
        self.fleet = fleet if fleet is not None else FleetManager(scheduler)
        self.tenant = job_row.get("tenant") or "default"
        self._queued_since: Optional[float] = None
        # set while a quota-change preemption drains: the stopped set
        # routes back into the admission queue instead of Stopped
        self._requeue_after_stop = False
        # the job's worker set; a finished worker's slot goes None until the
        # whole set drains (index == worker_index for assignment/commit fan-out)
        self.handles: list[Optional[WorkerHandle]] = []
        self.coordinator = None  # CheckpointCoordinator for multi-worker sets
        # ordered 2PC trail (metadata_durable / commit_sent ...); survives
        # worker-set restarts so chaos tests can audit the whole history
        self.checkpoint_event_log: list[tuple] = []
        self.sql: Optional[str] = None
        self.parallelism = 1
        self.restarts = 0
        self.restore_epoch: Optional[int] = None
        self.next_epoch = 1
        self.last_checkpoint_time = time.monotonic()
        self.running_since: Optional[float] = None
        self.stopping_epoch: Optional[int] = None
        self.rescale_to: Optional[int] = None
        # live evolution (versioned redeploy): the evolved SQL while the v1
        # set drains, and the blue/green gate armed between the evolved
        # restart and its first durable epoch (the cutover barrier)
        self.evolve_to: Optional[str] = None
        self._evolve_catchup = False
        self.failure: Optional[str] = None
        # stuck-checkpoint watchdog: epoch -> trigger time, plus the
        # consecutive-failure escalation counter and GC cadence counter
        self._inflight_epochs: dict[int, float] = {}
        self._ckpt_failures = 0
        self._epochs_since_gc = 0
        self._gc_thread: Optional[threading.Thread] = None
        self._last_stop_resend = 0.0
        # durable audit counters (survive worker-set restarts; failure
        # messages get overwritten by later recoveries)
        self.watchdog_failed_epochs = 0
        self.watchdog_escalations = 0
        # latest per-operator metrics snapshot per worker of the set;
        # merged (union by subtask label) before persisting, so no worker's
        # report overwrites another's operators
        self._metrics_by_worker: dict[int, dict] = {}
        from ..metrics import RateTracker

        self.rates = RateTracker(window_s=10.0)
        # the autoscaler's sensor layer (obs/health.py): rule set with
        # hysteresis evaluated every supervision tick over the latest
        # merged metrics snapshot; transitions emit HEALTH_* events
        self.health = HealthMonitor(job_id,
                                    on_transition=self._on_health_transition)
        # the actuator on top of those sensors (controller/autoscaler.py):
        # decides a target parallelism on the same tick and actuates it
        # through the normal desired_parallelism -> Rescaling drain path
        self.autoscaler = Autoscaler(job_id, emit=self._event)
        # the target of the autoscale currently actuating (None when no
        # autoscale is pending): AUTOSCALE_DONE fires only when a worker
        # set actually starts at exactly this parallelism
        self._autoscale_target: Optional[int] = None
        self._last_merged_metrics: Optional[dict] = None
        self._last_health_persist = 0.0
        # job event log: incremental flush cursor into the job_events table.
        # A restarted controller re-adopting the job seeds both the cursor
        # and the in-memory ring's seq counter from the DB's max persisted
        # seq, or every post-restart event would collide with an existing
        # (job, seq) row and be silently dropped by the idempotent flush
        self._events_flushed_seq = self.db.last_event_seq(job_id)
        events_recorder.ensure_seq_floor(job_id, self._events_flushed_seq)
        if self.state not in (JobState.CREATED, JobState.COMPILING,
                              JobState.QUEUED, JobState.RESTARTING) \
                and not self.is_terminal():
            # fresh controller adopting a LIVE job: the fleet ledger must
            # reflect its slots even if that briefly oversubscribes the
            # pool (free clamps at zero; pressure drains the overdraft).
            # RESTARTING is excluded: it is only entered from a terminal
            # state whose slots were released — a manual restart must
            # re-enter admission (the _step_inner restart path), not
            # adopt its way past a full pool and the tenant quota.
            pipeline = self.db.get_pipeline(job_row["pipeline_id"]) or {}
            par = int(job_row.get("desired_parallelism")
                      or pipeline.get("parallelism") or 1)
            self.fleet.adopt(self.job_id, self.tenant, demand_slots(
                int(job_row.get("n_workers") or 1), par))

    def _demand(self) -> int:
        """This job's slot demand: one slot per parallel lane, at least
        one per worker of its set (see fleet.demand_slots)."""
        return demand_slots(
            int(config().get("controller.workers-per-job") or 1),
            self.parallelism)

    def _event(self, level: str, code: str, message: str, **kw) -> None:
        events_recorder.record(self.job_id, level, code, message, **kw)

    def _flush_events(self) -> None:
        """Persist job events recorded (or ingested from workers) since the
        last flush — runs every step so the DB feed trails the ring by at
        most one supervision tick. The cursor advances only AFTER a
        successful write (a transient DB error retries the same events next
        tick instead of silently dropping them), and a failed flush must
        not take the supervision loop down with it."""
        evs = events_recorder.events(self.job_id,
                                     after_seq=self._events_flushed_seq)
        if not evs:
            return
        try:
            self.db.record_events(self.job_id, evs)
        except Exception:  # noqa: BLE001 - feed durability is best-effort
            _log.exception("job-event flush failed for %s; retrying next "
                           "tick", self.job_id)
            return
        self._events_flushed_seq = evs[-1]["seq"]

    def _pick_restore_epoch(self) -> Optional[int]:
        """The restore fallback ladder: verify-before-load. The newest
        complete epoch that passes integrity verification wins; corrupt or
        incomplete ones are QUARANTINED (marker preserved, never deleted —
        GC refuses them until an operator resolves) and the walk falls
        back to the next-older valid epoch. Sources rewind to the chosen
        epoch's checkpointed offsets, so replay covers the gap."""
        from ..state.integrity import latest_valid_checkpoint

        def on_quarantine(epoch: int, reason: str) -> None:
            self._event("ERROR", "CHECKPOINT_QUARANTINED",
                        f"checkpoint epoch {epoch} failed integrity "
                        f"verification and was quarantined: {reason[:400]}",
                        epoch=epoch, data={"reason": reason[:800]})

        epoch, skipped = latest_valid_checkpoint(
            self.storage_url, self.job_id, on_quarantine=on_quarantine)
        if skipped:
            self._event("WARN", "RESTORE_FELL_BACK",
                        f"restore fell back to epoch {epoch or 0} past "
                        f"{len(skipped)} quarantined epoch(s); sources "
                        f"rewind and replay covers the gap byte-exactly",
                        epoch=epoch,
                        data={"fallback_epoch": epoch, "skipped": skipped})
        return epoch

    def _on_health_transition(self, old: str, new: str, detail: dict) -> None:
        firing = [{"rule": r["rule"], "value": r["value"],
                   "threshold": r["threshold"]}
                  for r in detail["rules"] if r["firing"]]
        code = health_event_code(new)
        level = {"HEALTH_OK": "INFO", "HEALTH_DEGRADED": "WARN",
                 "HEALTH_CRITICAL": "ERROR"}[code]
        names = ", ".join(f["rule"] for f in firing) or "all rules clear"
        self._event(level, code, f"health {old} -> {new} ({names})",
                    data={"firing": firing})
        self.db.update_job(self.job_id, health=new)
        detail = {**detail,
                  "autoscaler": self.autoscaler.detail(self.parallelism)}
        self.db.record_health(self.job_id, new, detail)

    def _eval_health(self) -> None:
        from ..metrics import registry as metrics_registry

        health_on = bool(config().get("health.enabled", True))
        autoscale_on = self.autoscaler.enabled()
        if not health_on and not autoscale_on:
            return
        if health_on:
            detail = self.health.evaluate(self._last_merged_metrics,
                                          ckpt_failures=self._ckpt_failures)
            metrics_registry.set_job_health(self.job_id, self.health.state)
        else:
            # monitors off, autoscaler on: the /health payload still has
            # to carry the autoscaler readout (and the gauge must export)
            detail = {"state": self.health.state, "rules": []}
        # the /health payload doubles as the autoscaler's readout: rail
        # state, live signals, and the last decision ride every persist
        detail["autoscaler"] = self.autoscaler.detail(self.parallelism)
        if autoscale_on:
            metrics_registry.set_autoscaler_target(
                self.job_id, self.autoscaler.target(self.parallelism))
        # transitions persist immediately (_on_health_transition); between
        # them, refresh the per-rule observed values at ~1 Hz for /health
        now = time.monotonic()
        if now - self._last_health_persist >= 1.0:
            self._last_health_persist = now
            self.db.record_health(self.job_id, self.health.state, detail)

    # -- single-worker compatibility surface ---------------------------

    @property
    def handle(self) -> Optional[WorkerHandle]:
        """First live handle (the only one for single-worker jobs)."""
        for h in self.handles:
            if h is not None:
                return h
        return None

    @handle.setter
    def handle(self, value: Optional[WorkerHandle]) -> None:
        self.handles = [] if value is None else [value]

    # ------------------------------------------------------------------

    def _set_state(self, nxt: JobState, **fields) -> None:
        check_transition(self.state, nxt)
        self.state = nxt
        self.db.update_job(self.job_id, state=nxt.value, **fields)

    def is_terminal(self) -> bool:
        return self.state in (JobState.FAILED, JobState.FINISHED, JobState.STOPPED)

    # ------------------------------------------------------------------

    def step(self) -> None:
        """One supervision tick; cheap and non-blocking."""
        try:
            # chaos site `job_tick` (ctx: key=job id): delay=MS models a
            # melting job's slow supervision step (storage stall, wedged
            # drain) — the tick budget must detect and deprioritize it
            # while its neighbors keep their heartbeat/watchdog cadence
            from ..faults import fault_point

            fault_point("job_tick", key=self.job_id)
            self._step_inner()
        except Exception:  # noqa: BLE001 - job failure, not controller crash
            self.failure = traceback.format_exc()
            self._fail(self.failure)
        finally:
            # event-feed durability trails the ring by at most one tick
            self._flush_events()

    def _kill_all(self) -> None:
        for h in self.handles:
            if h is None:
                continue
            try:
                h.kill()
            except Exception:  # lint: waive LR102 — best-effort teardown of a worker set; members may already be gone
                pass
        self.handles = []

    def _fail(self, msg: str) -> None:
        self._kill_all()
        if not self.is_terminal():
            self._set_state(JobState.FAILED, failure_message=msg[-4000:])

    def _step_inner(self) -> None:
        job = self.db.get_job(self.job_id)
        if job is None:
            self._fail("job row deleted")
            return
        desired_stop = job["desired_stop"]

        if self.state == JobState.CREATED:
            self._set_state(JobState.COMPILING)
        elif self.state == JobState.COMPILING:
            self._compile(job)
        elif self.state == JobState.QUEUED:
            self._queued_tick(job)
        elif self.state == JobState.SCHEDULING:
            self._schedule(job)
        elif self.state in (JobState.RUNNING, JobState.CHECKPOINT_STOPPING,
                            JobState.STOPPING, JobState.FINISHING):
            self._supervise(desired_stop, job)
        elif self.state == JobState.RESCALING:
            # the old worker is draining behind a final checkpoint; keep
            # supervising it — _supervise's finished/failed handlers do the
            # actual Rescaling -> Scheduling hop (reference rescaling.rs:16)
            if self.handle is not None:
                self._supervise(desired_stop, job)
            else:
                # adopted mid-rescale by a fresh controller: treat like a
                # restart at the (already persisted) new parallelism
                self._finish_rescale(job)
        elif self.state == JobState.EVOLVING:
            # the v1 set is draining behind its final checkpoint; keep
            # supervising it — the finished/failed handlers do the actual
            # Evolving -> Scheduling hop after the plan-diff pass proves
            # (and persists) the state carry-over mapping
            if self.handle is not None:
                self._supervise(desired_stop, job)
            else:
                # adopted mid-evolve by a fresh controller: the drain is
                # over; finish the evolution from the persisted request
                self._finish_evolve(job)
        elif self.state in (JobState.RECOVERING, JobState.RESTARTING):
            restarts_allowed = config().get("pipeline.allowed-restarts")
            if self.state == JobState.RECOVERING and self.restarts > restarts_allowed:
                self._fail(f"exceeded allowed-restarts={restarts_allowed}: {self.failure}")
                return
            # a crash-restoring job still holds its fleet slots; a restart
            # of a TERMINAL job released them and must re-enter admission
            # (Queued when the shared pool or its tenant quota is full)
            if not self.fleet.holds(self.job_id) \
                    and not self._admit_or_queue(job):
                return
            self.restore_epoch = self._pick_restore_epoch()
            self._event("WARN", "RESTORE",
                        f"restoring worker set from epoch "
                        f"{self.restore_epoch or 0} (restart {self.restarts})",
                        epoch=self.restore_epoch,
                        data={"restarts": self.restarts})
            self._set_state(JobState.SCHEDULING, restarts=self.restarts,
                            restore_epoch=self.restore_epoch)

    def _finish_rescale(self, job: dict) -> None:
        """Old worker is gone; restore from the freshest checkpoint at the
        new parallelism (the state layer rescales via key-range-overlap
        file reads on restore)."""
        # re-read the request: the API may have accepted a NEWER target
        # after this drain was triggered — honor the freshest value
        fresh = self.db.get_job(self.job_id) or job
        target = fresh.get("desired_parallelism") or self.rescale_to
        self.rescale_to = None
        if self._autoscale_target is not None and (
                not target or int(target) != self._autoscale_target):
            # the drain completed toward a DIFFERENT parallelism — a newer
            # manual target superseded the autoscale (or the request was
            # cleared) — so no AUTOSCALE_DONE may fire for this restart,
            # nor for any later unrelated one
            self._autoscale_target = None
        if target:
            self.parallelism = int(target)
            self.db.set_pipeline_parallelism(job["pipeline_id"], int(target))
            # conditional clear: a request racing in after the re-read
            # above survives and triggers a follow-up rescale
            self.db.clear_desired_parallelism(self.job_id, int(target))
        # the transition is over: the ledger settles on the final demand
        # (a scale-down frees slots for the next admission pass)
        self.fleet.set_demand(self.job_id, self._demand())
        self.restore_epoch = self._pick_restore_epoch()
        self._event("WARN", "RESTORE",
                    f"restoring worker set from epoch "
                    f"{self.restore_epoch or 0} at parallelism "
                    f"{self.parallelism} (rescale)",
                    epoch=self.restore_epoch,
                    data={"parallelism": self.parallelism})
        self._set_state(JobState.SCHEDULING, restore_epoch=self.restore_epoch,
                        restarts=self.restarts)

    def _finish_evolve(self, job: dict) -> None:
        """The v1 set drained behind its final checkpoint. Re-prove the
        state carry-over with the plan-diff pass against THAT drain (the
        API's plan-time check may be stale by now), persist the evolution
        mapping next to the checkpoint it applies to, bump the pipeline
        version, and reschedule the evolved plan restoring through the
        mapping. A rejection here restarts the UNCHANGED plan from its own
        drain checkpoint — never a torn half-evolved lineage."""
        if not self._hydrate_from_pipeline(job):
            return
        fresh = self.db.get_job(self.job_id) or job
        new_sql = fresh.get("desired_query") or self.evolve_to
        self.evolve_to = None
        self.restore_epoch = self._pick_restore_epoch()
        if not new_sql or new_sql == self.sql:
            # request withdrawn (or no-op) between pickup and drain end:
            # the drained set just restarts unchanged
            self._set_state(JobState.SCHEDULING,
                            restore_epoch=self.restore_epoch,
                            restarts=self.restarts)
            return
        diff = None
        reject_reason = ""
        try:
            from ..analysis.plan_diff import diff_plans
            from ..sql import plan_query

            scope = self.db.list_connection_tables()
            old_graph = plan_query(self.sql, connection_tables=scope).graph
            new_graph = plan_query(new_sql, connection_tables=scope).graph
            diff = diff_plans(old_graph, new_graph)
        except Exception as exc:  # noqa: BLE001 - reject, don't kill the job
            reject_reason = f"evolved query failed to plan: {exc}"
        if diff is not None and diff.rejected:
            reject_reason = "; ".join(
                f"{d.rule_id}: {d.message}" for d in diff.diagnostics
                if d.severity.name == "ERROR")
        if reject_reason:
            self._event(
                "ERROR", "JOB_EVOLVE_CLASSIFIED",
                f"evolution rejected at the drain barrier: "
                f"{reject_reason[:600]}",
                data={"rejected": True,
                      "classifications":
                          [c.to_json() for c in diff.classifications]
                          if diff is not None else []})
            self.db.clear_desired_query(self.job_id, new_sql)
            # the drained v1 restarts UNCHANGED from its own checkpoint
            self._set_state(JobState.SCHEDULING,
                            restore_epoch=self.restore_epoch,
                            restarts=self.restarts)
            return
        counts: dict[str, int] = {}
        for c in diff.classifications:
            counts[c.action] = counts.get(c.action, 0) + 1
        if self.restore_epoch:
            # the mapping is epoch-keyed and atomically written: a crash
            # anywhere after this point re-reads the SAME proof and the
            # restore stays deterministic
            from ..state.tables import write_evolution_mapping

            write_evolution_mapping(self.storage_url, self.job_id,
                                    self.restore_epoch, diff.mapping)
        version = self.db.evolve_pipeline_query(job["pipeline_id"], new_sql)
        self.db.clear_desired_query(self.job_id, new_sql)
        self.sql = new_sql
        # blue/green: phase-2 commits of the evolved set are withheld
        # until its first durable epoch (the cutover barrier, see
        # _epoch_durable); until then only staged output exists
        self._evolve_catchup = True
        self._event(
            "INFO", "JOB_EVOLVE_CLASSIFIED",
            "plan diff proved the carry-over: "
            + ", ".join(f"{counts.get(k, 0)} {k}" for k in
                        ("carried", "rebuilt", "dropped", "stateless"))
            + f"; pipeline version {version}, restoring from epoch "
              f"{self.restore_epoch or 0}",
            epoch=self.restore_epoch,
            data={"rejected": False, "version": version,
                  "classifications":
                      [c.to_json() for c in diff.classifications]})
        self._set_state(JobState.SCHEDULING,
                        restore_epoch=self.restore_epoch,
                        restarts=self.restarts)

    # ------------------------------------------------------------------

    def _compile(self, job: dict) -> None:
        from ..sql import plan_query

        pipeline = self.db.get_pipeline(job["pipeline_id"])
        if pipeline is None:
            self._fail("pipeline deleted")
            return
        self.sql = pipeline["query"]
        self.parallelism = int(pipeline["parallelism"])
        # a rescale accepted before the job ever ran starts the worker at
        # the new scale directly — no wasted drain cycle after Running
        want = job.get("desired_parallelism")
        if want:
            self.parallelism = int(want)
            self.db.set_pipeline_parallelism(job["pipeline_id"], int(want))
            self.db.clear_desired_parallelism(self.job_id, int(want))
        # validate with registered connection tables in scope; workers get
        # the planned IR (graph_json) so they need no DB access
        plan_query(self.sql, connection_tables=self.db.list_connection_tables())
        if not self._admit_or_queue(job):
            return
        self._set_state(JobState.SCHEDULING)

    def _hydrate_from_pipeline(self, job: dict) -> bool:
        """Load sql/parallelism for a job this controller never compiled
        (fresh controller adopting a Restarting/Recovering/Queued job)."""
        if self.sql is not None:
            return True
        pipeline = self.db.get_pipeline(job["pipeline_id"])
        if pipeline is None:
            self._fail("pipeline deleted")
            return False
        self.sql = pipeline["query"]
        self.parallelism = int(job.get("desired_parallelism")
                               or pipeline["parallelism"])
        self.restarts = int(job.get("restarts") or 0)
        return True

    def _admit_or_queue(self, job: dict) -> bool:
        """Ask the fleet for this job's slots. True = admitted, proceed;
        False = the state already moved (Queued on full pool / tenant at
        quota, Failed on a structural quota rejection)."""
        if not self._hydrate_from_pipeline(job):
            return False
        slots = self._demand()
        verdict, reason = self.fleet.admit(self.job_id, self.tenant, slots)
        data = {"tenant": self.tenant, "slots": slots, "reason": reason}
        if verdict == "rejected":
            self._event("ERROR", "JOB_REJECTED",
                        f"admission rejected: {reason}", data=data)
            self._fail(f"admission rejected: {reason}")
            return False
        if verdict == "queued":
            self._queued_since = time.monotonic()
            self._event("INFO", "JOB_QUEUED",
                        f"waiting for admission: {reason}", data=data)
            self._set_state(JobState.QUEUED)
            return False
        if self.fleet.pool_slots() is not None:
            # decision-point visibility only when the fleet is bounded —
            # the unlimited pass-through default stays event-silent
            self._event("INFO", "JOB_ADMITTED",
                        f"admitted into shared capacity ({slots} slots)",
                        data=data)
        return True

    def _queued_tick(self, job: dict) -> None:
        """One supervision tick in QUEUED: react to a cancel, otherwise
        wait for the fleet's deficit-round-robin pass to grant the slots
        (capacity freed by any terminal job triggers re-admission on the
        next tick)."""
        if not self._hydrate_from_pipeline(job):
            return
        if job.get("desired_stop"):
            # cancel path: nothing is running, stop takes effect now
            self.fleet.release(self.job_id)
            self._event("INFO", "JOB_QUEUED",
                        "queued job cancelled by a stop request")
            self._set_state(JobState.STOPPED)
            return
        if not self.fleet.holds(self.job_id) \
                and self.fleet.queue_position(self.job_id) is None:
            # adopted mid-queue by a fresh controller whose fleet ledger
            # is empty: re-enter at the PERSISTED position, so N adopted
            # jobs restore the original FIFO order regardless of which
            # controller ticks first
            self.fleet.restore_queued(
                self.job_id, self.tenant, self._demand(),
                position=self.db.fleet_queue_position(self.job_id))
        if not self.fleet.should_admit(self.job_id):
            return
        waited = (time.monotonic() - self._queued_since
                  if self._queued_since is not None else 0.0)
        self._event("INFO", "JOB_ADMITTED",
                    f"admitted after {waited:.1f}s queued "
                    f"({self._demand()} slots)",
                    data={"tenant": self.tenant, "slots": self._demand(),
                          "waited_s": round(waited, 3)})
        self.fleet.clear_backoff(self.job_id)
        # a preempted (or 409-bounced) job resumes from its freshest
        # checkpoint; a first-time job has none and starts clean
        self.restore_epoch = self._pick_restore_epoch()
        self._set_state(JobState.SCHEDULING,
                        restore_epoch=self.restore_epoch)

    def _requeue_for_capacity(self, reason: str) -> None:
        """Placement was rejected on capacity (node-daemon 409, injected
        admission fault): tear down whatever partially placed, re-queue at
        the head of the tenant queue with deterministic backoff — never a
        job failure, never a restart-budget token."""
        self._kill_all()
        self.fleet.requeue(self.job_id, self.tenant, self._demand(),
                           backoff=True)
        self._queued_since = time.monotonic()
        backoff = self.fleet.backoff_remaining(self.job_id)
        self._event("WARN", "JOB_QUEUED",
                    f"placement rejected; re-queued with {backoff:.1f}s "
                    f"backoff: {reason.splitlines()[0][:200]}",
                    data={"tenant": self.tenant, "slots": self._demand(),
                          "backoff_s": round(backoff, 3), "reason": "409"})
        self._set_state(JobState.QUEUED)

    def _compile_graph(self):
        """Plan once in the control plane and ship the dataflow IR to
        workers as data (reference: the API compiles SQL to a protobuf
        ArrowProgram and StartExecutionReq carries it — workers never
        re-plan). Falls back to shipping SQL when a config carries live
        objects the IR cannot serialize (e.g. in-process lookup tables)."""
        try:
            from ..sql import plan_query
            from ..sql.planner import set_parallelism

            pp = plan_query(self.sql,
                            connection_tables=self.db.list_connection_tables())
            if self.parallelism > 1:
                set_parallelism(pp.graph, self.parallelism)
            dumped = pp.graph.dumps()
            from ..graph import Graph

            Graph.loads(dumped)  # round-trip check before shipping
            return dumped
        except Exception:
            return None

    def _schedule(self, job: dict) -> None:
        if self.sql is None:
            # a fresh JobController adopting a Restarting/Recovering job
            # (reference: Restarting passes back through Compiling)
            pipeline = self.db.get_pipeline(job["pipeline_id"])
            if pipeline is None:
                self._fail("pipeline deleted")
                return
            self.sql = pipeline["query"]
            self.parallelism = int(pipeline["parallelism"])
            self.restarts = int(job["restarts"])
        graph_json = self._compile_graph()
        n_workers = int(config().get("controller.workers-per-job") or 1)
        from ..faults import InjectedFault, fault_point

        try:
            # chaos site `admission`: a node 409 (or delay) at the exact
            # placement moment, injectable for every scheduler. Recovery
            # is re-queue with deterministic backoff, never job failure.
            fault_point("admission", key=self.job_id, job=self.job_id)
            self.handles = list(self.scheduler.start_workers(
                self.sql, self.job_id, self.parallelism, self.restore_epoch,
                self.storage_url, udf_specs=self.db.list_udfs(),
                graph_json=graph_json, n_workers=n_workers,
            ))
        except (PlacementFull, InjectedFault) as e:
            self._requeue_for_capacity(str(e))
            return
        # a placement landed: the consecutive-409 backoff streak resets
        self.fleet.clear_backoff(self.job_id)
        self.coordinator = None
        if len(self.handles) > 1:
            # multi-worker set: this controller owns checkpoint coordination
            from .checkpoint_state import CheckpointCoordinator, compute_assignment

            _assignment, expected, _n = compute_assignment(
                graph_json, len(self.handles))
            # the coordinator writes the job-level metadata markers for
            # this set, so IT stamps the plan fingerprint (single workers
            # stamp their own in the engine); computed over the logical
            # pre-chaining graph so both sides always agree
            plan_hash = None
            try:
                from ..analysis.plan_diff import plan_fingerprint
                from ..graph import Graph

                plan_hash = plan_fingerprint(Graph.loads(graph_json))
            except Exception:  # noqa: BLE001 - stamping is best-effort
                plan_hash = None
            self.coordinator = CheckpointCoordinator(
                self.job_id, self.storage_url, expected,
                event_log=self.checkpoint_event_log, plan_hash=plan_hash)
        # a fresh worker set starts a fresh checkpoint ledger (and a fresh
        # metrics view: the old set's counters restart from zero)
        self._inflight_epochs = {}
        self._ckpt_failures = 0
        self._metrics_by_worker = {}
        # the old set's final merged snapshot is stale the moment the new
        # set exists: health/autoscaler must not act on its (typically
        # terrible) last readings until a fresh report lands
        self._last_merged_metrics = None
        # stale RateTracker points against the old set's (larger) totals
        # would make (new - old)/dt negative for a whole rate window
        self.rates.reset()
        self.db.update_job(self.job_id, n_workers=len(self.handles),
                           health=self.health.state)
        self.running_since = time.monotonic()
        self.last_checkpoint_time = time.monotonic()
        if self.restore_epoch:
            self.next_epoch = self.restore_epoch + 1
        self._set_state(JobState.RUNNING)
        # DONE only when this (re)start actually landed the decided
        # target — a crash restore racing in between the decision and
        # the rescale pickup restarts at the OLD parallelism first (the
        # still-pending desired_parallelism completes the scale on a
        # later pass through here), and a transition superseded by a
        # newer manual target cleared the flag in _finish_rescale
        if self._autoscale_target is not None \
                and self.parallelism == self._autoscale_target:
            self._autoscale_target = None
            self._event("INFO", "AUTOSCALE_DONE",
                        f"worker set running at parallelism "
                        f"{self.parallelism} (autoscale)",
                        data={"parallelism": self.parallelism,
                              "restore_epoch": self.restore_epoch})
        # any (re)start arms the autoscaler cooldown: post-restart metrics
        # are warm-up noise whether a rescale, a crash restore, or a fresh
        # schedule caused it (this also clears an in-flight autoscale)
        self.autoscaler.on_worker_set_started()

    # ------------------------------------------------- worker-set control

    def _trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        """Fan a checkpoint trigger to the whole worker set (each engine
        injects barriers into ITS local source subtasks) and arm the
        stuck-epoch watchdog."""
        if self.coordinator is not None:
            self.coordinator.begin(epoch)
        obs_trace.recorder.record(self.job_id, epoch, "trigger")
        self._inflight_epochs[epoch] = time.monotonic()
        rescaling = then_stop and (self.rescale_to is not None
                                   or self.state == JobState.RESCALING)
        evolving = then_stop and (self.evolve_to is not None
                                  or self.state == JobState.EVOLVING)
        from ..faults import fault_point

        for widx, h in enumerate(self.handles):
            if h is None:
                continue
            if rescaling:
                # chaos site `rescale`: the scale command to one worker
                # can be lost or delayed mid-transition. Recovery is
                # protocol-level: the unreached worker never acks, the
                # stuck-epoch watchdog declares the drain epoch failed
                # and re-triggers it at a fresh epoch (then_stop intact)
                verdict = fault_point("rescale", epoch=epoch, worker=widx)
                if verdict is not None and verdict[0] == "drop":
                    continue
            if evolving:
                # chaos site `evolve_drain`: the final-checkpoint drain
                # command of a live evolution is lost to one worker.
                # Recovery mirrors `rescale`: the unreached worker never
                # acks, the stuck-epoch watchdog re-triggers the drain at
                # a fresh epoch, and the evolved plan restores exactly the
                # lineage that drain proved — never a torn one
                verdict = fault_point("evolve_drain", epoch=epoch,
                                      worker=widx)
                if verdict is not None and verdict[0] == "drop":
                    continue
            h.trigger_checkpoint(epoch, then_stop=then_stop)

    def _epoch_durable(self, epoch: int) -> None:
        """An epoch's job-level metadata marker is durable (written by the
        engine in single-worker mode, by the coordinator at global coverage
        for worker sets). Record it, then — and only then — fan phase-2
        commits out (the coordinator's event log proves the ordering;
        single workers self-commit inside the engine)."""
        self._inflight_epochs.pop(epoch, None)
        self._ckpt_failures = 0
        obs_trace.recorder.record(self.job_id, epoch, "metadata_durable")
        if self._evolve_catchup and epoch != (self.restore_epoch or 0):
            # blue/green cutover: the evolved (v2) set's first durable
            # epoch proves it processed past the v1 drain watermark (its
            # sources resumed from the carried offsets), so the withheld
            # phase-2 commits may now be released — atomically at this
            # barrier, via the cumulative commit delivery. For coordinated
            # sets the `evolve_cutover` chaos site fires HERE, before any
            # commit leaves the controller; single-worker engines fire it
            # themselves at the same protocol point (engine.py).
            self._evolve_catchup = False
            if self.coordinator is not None:
                from ..faults import fault_point

                try:
                    fault_point("evolve_cutover", epoch=epoch,
                                key=self.job_id)
                except Exception as exc:  # noqa: BLE001 - injected crash
                    # crash AT the barrier: the epoch is durable but no
                    # commit was released. Re-arm the gate and take the
                    # normal recovery path — the restored set re-delivers
                    # the withheld commits cumulatively
                    # (COMMIT_REDELIVERED): one committed lineage, never
                    # two
                    self._evolve_catchup = True
                    self._on_worker_failed(
                        f"crash injected at the evolve cutover barrier "
                        f"(epoch {epoch}): {exc}",
                        self.db.get_job(self.job_id) or {})
                    return
            self._event("INFO", "JOB_EVOLVE_CUTOVER",
                        f"cutover: evolved set caught up and went durable "
                        f"at epoch {epoch}; releasing withheld commits",
                        epoch=epoch)
            self._event("INFO", "JOB_EVOLVE_DONE",
                        "evolution complete: the evolved plan owns the "
                        "single committed lineage",
                        epoch=epoch)
        if self.coordinator is not None:
            self.coordinator.send_commits(
                epoch,
                [h.send_commit if h is not None else None for h in self.handles])
        # the epoch's span tree is as complete as it gets: derive the phase
        # durations (align/snapshot/ack/commit), feed the histograms, and
        # persist both to the DB for `top`/`trace` and the API
        events = obs_trace.recorder.events(self.job_id, epoch)
        phases = obs_trace.phase_durations(events)
        if phases:
            from ..metrics import registry as metrics_registry

            metrics_registry.observe_epoch_phases(self.job_id, phases)
        self.db.record_checkpoint(self.job_id, epoch, "complete",
                                  phases=phases or None)
        self.db.update_job(self.job_id, checkpoint_epoch=epoch)
        self.db.record_trace(self.job_id, epoch, events)
        if self.state == JobState.CHECKPOINT_STOPPING and epoch == self.stopping_epoch:
            self._set_state(JobState.STOPPING)
        self._maybe_gc(epoch)

    def _maybe_gc(self, newest_epoch: int) -> None:
        """Controller-driven checkpoint GC: every
        ``checkpoint.compaction.epochs`` completed epochs, compact the
        newest globally-complete epoch's shards and drop everything older.
        ``newest_epoch`` is by construction the newest complete one, so the
        cleanup floor can never delete past a restorable checkpoint (and
        cleanup_checkpoints keeps the "final" drained-source snapshots).
        Runs on a background thread — storage-heavy compaction must not
        stall the supervision tick's heartbeat/watchdog checks for every
        other job (the reference triggers compaction asynchronously too)."""
        every = int(config().get("checkpoint.compaction.epochs") or 0)
        if every <= 0:
            return
        self._epochs_since_gc += 1
        if self._epochs_since_gc < every:
            return
        if self._gc_thread is not None and self._gc_thread.is_alive():
            return  # previous GC still running; counter stays armed
        self._epochs_since_gc = 0

        def _run_gc() -> None:
            from ..state.spill import cleanup_spill_runs
            from ..state.tables import cleanup_checkpoints, compact_job

            try:
                compact_job(self.storage_url, self.job_id, newest_epoch)
                cleanup_checkpoints(self.storage_url, self.job_id, newest_epoch)
                # tiered-state runs outlive single epochs; with the old
                # epochs gone, delete every run no surviving checkpoint
                # references (fresh post-checkpoint runs are epoch-tagged
                # and always kept)
                cleanup_spill_runs(self.storage_url, self.job_id, newest_epoch)
                self.db.record_checkpoint(self.job_id, newest_epoch, "compacted")
            except Exception:  # noqa: BLE001 - GC is best-effort maintenance
                _log.exception("checkpoint GC failed for %s at epoch %d",
                               self.job_id, newest_epoch)

        self._gc_thread = threading.Thread(
            target=_run_gc, daemon=True, name=f"ckpt-gc-{self.job_id}")
        self._gc_thread.start()

    def _record_worker_metrics(self, widx: int, data: dict) -> None:
        """Merge one worker's per-operator snapshot into the job view (union
        by subtask label — under an assignment each worker owns a disjoint
        slice, so a 2-worker set's snapshot carries BOTH workers' subtasks),
        refresh the windowed rates, and persist for the API/`top`."""
        from ..metrics import merge_job_metrics

        self._metrics_by_worker[widx] = data
        merged = merge_job_metrics(self._metrics_by_worker.values())
        self._last_merged_metrics = merged  # the health rules' input
        now = time.monotonic()
        for op, m in merged.items():
            self.rates.observe(
                f"{op}.sent", int(m.get("arroyo_worker_messages_sent", 0)), now)
            self.rates.observe(
                f"{op}.recv", int(m.get("arroyo_worker_messages_recv", 0)), now)
            m["messages_per_sec"] = round(self.rates.rate(f"{op}.sent"), 2)
            m["messages_recv_per_sec"] = round(self.rates.rate(f"{op}.recv"), 2)
        if merged:
            self.db.record_metrics(self.job_id, merged)
            # compact per-job cost profile (obs.profile): the queryable
            # snapshot behind /profile and `arroyo_tpu explain`
            from ..obs.profile import job_profile

            self.db.record_profile(self.job_id, job_profile(merged))

    def _on_worker_finished(self, widx: int, h: WorkerHandle, job: dict) -> bool:
        """One worker of the set drained. Returns True when the whole set
        is done and the job-level transition happened."""
        # release the exited worker's resources (temp sql/udf files,
        # pipes); for a finished process this is pure cleanup
        try:
            h.kill()
        except Exception:  # lint: waive LR102 — best-effort kill during finished-worker cleanup; process is already gone
            pass
        self.handles[widx] = None
        if any(x is not None for x in self.handles):
            return False  # the rest of the set is still draining
        self.handles = []
        if self.state == JobState.RESCALING:
            self._finish_rescale(job)
            return True
        if self.state == JobState.EVOLVING:
            self._finish_evolve(job)
            return True
        if self.state in (JobState.STOPPING, JobState.CHECKPOINT_STOPPING):
            if self._requeue_after_stop:
                self._finish_preemption()
            else:
                self._set_state(JobState.STOPPED)
        else:
            if self._evolve_catchup:
                # the evolved set drained to exhaustion before a periodic
                # epoch could fire: its final flush IS the cutover barrier —
                # everything it produced is committed exactly once at finish
                self._evolve_catchup = False
                self._event("INFO", "JOB_EVOLVE_CUTOVER",
                            "cutover: evolved set drained to completion; "
                            "its final flush releases the withheld commits")
                self._event("INFO", "JOB_EVOLVE_DONE",
                            "evolution complete: the evolved plan owns the "
                            "single committed lineage")
            self._set_state(JobState.FINISHING)
            self._set_state(JobState.FINISHED)
        return True

    def _finish_preemption(self) -> None:
        """A quota-change preemption finished draining: back into the
        admission queue (no backoff — nothing was rejected), resuming from
        the drain checkpoint once the tenant fits its quota again."""
        self._requeue_after_stop = False
        self.fleet.requeue(self.job_id, self.tenant, self._demand())
        self._queued_since = time.monotonic()
        self._event("INFO", "JOB_QUEUED",
                    "preempted worker set drained; job re-entered the "
                    "admission queue",
                    data={"tenant": self.tenant, "slots": self._demand(),
                          "reason": "preempted"})
        self._set_state(JobState.QUEUED)

    def _on_worker_failed(self, error: str, job: dict,
                          worker: Optional[int] = None) -> None:
        """Any worker of the set failing (crash, heartbeat loss, wedged
        checkpoints) takes the WHOLE set down: the survivors hold state the
        failed worker's subtasks fed, so the only consistent restart is the
        full set from the last globally complete checkpoint. State-aware:
        a set dying mid-rescale still rescales, a set dying while stopping
        just stops (Stopping/CheckpointStopping have no Recovering edge)."""
        self.failure = error
        self._event("ERROR", "WORKER_LOST",
                    (error or "worker failure").splitlines()[0][:300],
                    worker=worker)
        self._kill_all()
        self.restarts += 1
        if self.state == JobState.RESCALING:
            # drain failed mid-rescale: still proceed to the new
            # parallelism from whatever checkpoint exists — but an
            # autoscaler-initiated transition that got disrupted arms the
            # exponential backoff before its NEXT decision
            self.autoscaler.on_scale_disrupted(error or "worker failure")
            self._finish_rescale(job)
        elif self.state == JobState.EVOLVING:
            # drain died mid-evolve: the evolution still proceeds, but
            # from the freshest COMPLETE checkpoint — the plan-diff
            # mapping is written against whatever epoch the restore
            # actually uses, so a torn drain can never split the lineage
            self._finish_evolve(job)
        elif self.state in (JobState.STOPPING, JobState.CHECKPOINT_STOPPING):
            if self._requeue_after_stop:
                # the preemption drain died mid-flight; the job still
                # re-queues and will restore from its last complete
                # checkpoint when re-admitted
                self._finish_preemption()
            else:
                self._set_state(JobState.STOPPED)
        else:
            self._set_state(JobState.RECOVERING,
                            failure_message=(self.failure or "")[-4000:])

    def _on_stuck_epochs(self, stuck: list[int], job: dict) -> bool:
        """``checkpoint.timeout-ms`` watchdog: a wedged epoch is declared
        failed, its torn shards are subsumed (they have no metadata marker,
        so restore already ignores them — deleting cannot lose state), and
        the checkpoint is retried at a fresh epoch. After
        ``checkpoint.max-consecutive-failures`` the whole set is restored
        from the last globally complete checkpoint. Returns True when the
        escalation ended this supervision pass."""
        outstanding: list = []
        to_subsume: list[int] = []
        wedge_report = ""
        for epoch in stuck:
            self._inflight_epochs.pop(epoch, None)
            if self.coordinator is not None:
                outstanding = self.coordinator.outstanding(epoch) or outstanding
                # forget FIRST (synchronously): late acks for the epoch are
                # dropped from here on, so deleting its shards cannot race a
                # still-completing worker into a torn-but-"complete" epoch
                self.coordinator.forget(epoch)
                to_subsume.append(epoch)
            # single-worker jobs get NO subsume: the engine owns completion
            # there and has no forget() — deleting shards could race a late-
            # unwedging subtask whose ack then publishes a metadata marker
            # over the emptied directory (silent state loss on restore); a
            # torn epoch without its marker is invisible anyway
            self.db.record_checkpoint(self.job_id, epoch, "failed")
            self._event(
                "WARN", "EPOCH_WEDGED",
                f"epoch {epoch} exceeded checkpoint.timeout-ms; torn shards "
                "subsumed, retrying at a fresh epoch",
                epoch=epoch,
                data={"unacked": [list(s) for s in outstanding]})
            # attach the epoch's trace timeline: the wedge diagnostic names
            # the exact subtask whose barrier never arrived / never acked,
            # and the persisted trace makes the postmortem queryable
            events = obs_trace.recorder.events(self.job_id, epoch)
            wedge_report = obs_trace.timeline_report(
                self.job_id, epoch, events,
                expected=self.coordinator.expected
                if self.coordinator is not None else None)
            self.db.record_trace(self.job_id, epoch, events)
            self._ckpt_failures += 1
            self.watchdog_failed_epochs += 1
        if to_subsume:
            # storage deletions off the supervision tick (same reason GC is
            # backgrounded: the watchdog fires exactly when storage is slow)
            def _subsume(epochs=tuple(to_subsume)) -> None:
                from ..state.tables import subsume_torn_epoch

                for e in epochs:
                    try:
                        subsume_torn_epoch(self.storage_url, self.job_id, e)
                    except Exception:  # noqa: BLE001 - orphans stay invisible
                        _log.exception("subsume of torn epoch %d failed for %s",
                                       e, self.job_id)

            threading.Thread(target=_subsume, daemon=True,
                             name=f"subsume-{self.job_id}").start()
        max_fail = int(config().get("checkpoint.max-consecutive-failures") or 3)
        detail = f" (unacked subtasks: {outstanding})" if outstanding else ""
        if self._ckpt_failures >= max_fail:
            self.watchdog_escalations += 1
            self._on_worker_failed(
                f"checkpoint wedged {self._ckpt_failures} consecutive times "
                f"(last epoch {stuck[-1]}){detail}; restoring the worker set "
                "from the last globally complete checkpoint\n"
                f"{wedge_report}", job)
            return True
        # retry at a FRESH epoch number (the wedged one is subsumed; late
        # acks for it are dropped by the coordinator)
        retry = self.next_epoch
        self.next_epoch += 1
        then_stop = False
        if self.stopping_epoch in stuck and self.state in (
                JobState.CHECKPOINT_STOPPING, JobState.RESCALING,
                JobState.EVOLVING):
            self.stopping_epoch = retry
            then_stop = True
        self._trigger_checkpoint(retry, then_stop=then_stop)
        self.last_checkpoint_time = time.monotonic()
        return False

    def _supervise(self, desired_stop: Optional[str], job: dict) -> None:
        assert self.handle is not None
        cfgv = config()
        # healthy-duration resets the restart budget (default.toml:8 analog)
        healthy_ms = cfgv.get("pipeline.healthy-duration-ms")
        if (self.restarts and self.running_since is not None
                and (time.monotonic() - self.running_since) * 1000 >= healthy_ms):
            self.restarts = 0
            self.db.update_job(self.job_id, restarts=0)

        # liveness snapshot BEFORE draining events: a worker that exits
        # mid-tick (finished/failed posted right after our poll) must be
        # diagnosed from its own terminal event on the NEXT tick, not
        # misreported as a heartbeat loss by the check below
        alive_before = [h is not None and h.alive() for h in self.handles]
        for widx, h in enumerate(list(self.handles)):
            if h is None:
                continue  # this worker already drained
            for ev in h.poll_events():
                kind = ev.get("event")
                if kind == "sink_data":
                    self.db.record_output(self.job_id, ev.get("lines", []))
                elif kind == "metrics":
                    data = ev.get("data") or {}
                    if data:
                        self._record_worker_metrics(widx, data)
                elif kind == "span":
                    # a worker subprocess relayed an epoch-lifecycle span;
                    # the controller's recorder holds the whole job timeline
                    obs_trace.recorder.record(
                        self.job_id, int(ev["epoch"]), ev["name"],
                        ev.get("node"), ev.get("subtask"), ev.get("worker"),
                        ev.get("t_us"))
                elif kind == "log":
                    # a worker subprocess relayed a structured job event
                    # (OPERATOR_PANIC, COMMIT_REDELIVERED, bridged stdlib
                    # records, ...); the controller's feed is authoritative
                    events_recorder.ingest(self.job_id, ev.get("data") or {})
                elif kind == "checkpoint_completed":
                    if self.coordinator is not None:
                        continue  # coordinated sets: durability is decided HERE
                    self._epoch_durable(int(ev["epoch"]))
                elif kind == "subtask_acked" and self.coordinator is not None:
                    durable = self.coordinator.on_ack(
                        int(ev["epoch"]), (ev["node"], int(ev["subtask"])),
                        integrity=ev.get("integrity"))
                    if durable is not None:
                        self._epoch_durable(durable)
                elif kind == "subtask_finished" and self.coordinator is not None:
                    for e in self.coordinator.on_task_finished(
                            (ev["node"], int(ev["subtask"]))):
                        self._epoch_durable(e)
                elif kind == "finished":
                    if self._on_worker_finished(widx, h, job):
                        return
                    break  # slot emptied; finished is a worker's last event
                elif kind == "failed":
                    err = ev.get("error", "unknown worker failure")
                    from .scheduler import NodeScheduler

                    if err.startswith("placement failed") \
                            and self.state == JobState.RUNNING \
                            and NodeScheduler._capacity_reason(err):
                        # a deferred (lazy) node placement timed out on
                        # CAPACITY (409 / no free slots / no daemons):
                        # the job never actually ran — re-queue with
                        # backoff instead of burning a restart-budget
                        # token through _on_worker_failed. Hard placement
                        # errors (a daemon answering 500) still take the
                        # normal failure path so the restart budget can
                        # cap a persistent misconfiguration.
                        self._requeue_for_capacity(err)
                        return
                    self._on_worker_failed(err, job, worker=widx)
                    return

        # health monitors: every supervision tick evaluates the rule set
        # over the latest merged metrics (hysteresis inside the monitor)
        self._eval_health()

        # heartbeat / liveness per worker (reference worker-heartbeat-timeout)
        hb_timeout = cfgv.get("pipeline.worker-heartbeat-timeout-ms") / 1000
        for widx, h in enumerate(self.handles):
            if h is None:
                continue
            dead = not (alive_before[widx] if widx < len(alive_before) else True) \
                and not h.alive()
            if dead or (
                time.monotonic() - h.last_heartbeat() > hb_timeout
            ):
                self._on_worker_failed(
                    f"worker {widx} lost (heartbeat timeout)", job,
                    worker=widx)
                return

        # stuck-checkpoint watchdog (checkpoint.timeout-ms)
        timeout_ms = cfgv.get("checkpoint.timeout-ms") or 0
        if timeout_ms and self._inflight_epochs and self.state in (
                JobState.RUNNING, JobState.CHECKPOINT_STOPPING,
                JobState.RESCALING, JobState.EVOLVING):
            now = time.monotonic()
            stuck = [e for e, t0 in sorted(self._inflight_epochs.items())
                     if (now - t0) * 1000 >= timeout_ms]
            if stuck and self._on_stuck_epochs(stuck, job):
                return

        # a drop-prone control plane (controller_rpc chaos) may lose the stop
        # command; stop is idempotent, so re-send it while draining rather
        # than wedging in Stopping forever
        if self.state == JobState.STOPPING and (
                time.monotonic() - self._last_stop_resend >= 1.0):
            self._last_stop_resend = time.monotonic()
            for h in self.handles:
                if h is not None:
                    h.stop()

        # quota-change preemption: the fleet marked this job (its tenant's
        # quota dropped below current usage) — drain behind a final
        # checkpoint, then back into the admission queue (JOB_PREEMPTED ->
        # drained -> JOB_QUEUED), restoring from that checkpoint once the
        # tenant fits again
        if self.state == JobState.RUNNING \
                and self.fleet.take_preemption(self.job_id):
            self._event("WARN", "JOB_PREEMPTED",
                        f"tenant {self.tenant!r} over quota after a quota "
                        "change; draining behind a final checkpoint and "
                        "re-queueing",
                        data={"tenant": self.tenant,
                              "slots": self._demand()})
            self._requeue_after_stop = True
            self.stopping_epoch = self.next_epoch
            self.next_epoch += 1
            self._trigger_checkpoint(self.stopping_epoch, then_stop=True)
            self._set_state(JobState.CHECKPOINT_STOPPING)
            return

        # elastic autoscaler: sustained pressure (or proven headroom) on
        # the merged metrics becomes a desired_parallelism the rescale
        # block below actuates through the normal drain/restore path. A
        # manual request already in flight always wins — the loop never
        # fights the operator — and a non-Running tick only resets the
        # hysteresis counters.
        can_scale = (self.state == JobState.RUNNING and not desired_stop
                     and not job.get("desired_parallelism")
                     # a pending live evolution owns the next drain cycle:
                     # the autoscaler must not wedge a rescale in front of it
                     and not job.get("desired_query"))
        target = self.autoscaler.evaluate(
            self._last_merged_metrics if can_scale else None,
            running=can_scale, parallelism=self.parallelism,
            ckpt_failures=self._ckpt_failures)
        if target is not None and target > self.parallelism:
            # a scale-up needs extra fleet slots BEFORE it actuates: a
            # pool that cannot place it turns the decision into fleet
            # pressure (the fleet loop grows the pool; the re-armed
            # hysteresis re-fires the decision once it has) instead of a
            # doomed drain/restore cycle
            grow = demand_slots(len(self.handles) or 1, target)
            if not self.fleet.try_grow(self.job_id, grow):
                self.autoscaler.on_capacity_blocked(self.parallelism, target)
                target = None
        if target is not None:
            # compare-and-set: a manual PATCH landing between this tick's
            # job-row read and here must win, not be clobbered
            if not self.db.set_desired_parallelism_if_unset(
                    self.job_id, target):
                self.autoscaler.abandon_in_flight()
            else:
                self._autoscale_target = target
                self._event("INFO", "AUTOSCALE_STARTED",
                            f"autoscale {self.parallelism} -> {target}: "
                            "draining the set behind a final checkpoint",
                            data={"from": self.parallelism, "to": target})
                job = dict(job)
                job["desired_parallelism"] = target  # same-tick pickup below

        # rescale requests from the API (reference states/rescaling.rs:1-70):
        # checkpoint-and-stop the old worker set, then reschedule at the new
        # parallelism restoring from that final checkpoint
        if self.state == JobState.RUNNING and not desired_stop:
            want = job.get("desired_parallelism")
            if want and int(want) != self.parallelism:
                self.rescale_to = int(want)
                # the fleet ledger carries the transition's worst case
                # (old lanes still live while the drain runs); manual
                # requests always win even if that oversubscribes — the
                # overdraft reads as fleet pressure and grows the pool
                self.fleet.set_demand(self.job_id, demand_slots(
                    len(self.handles) or 1,
                    max(self.parallelism, int(want))))
                self._event("INFO", "RESCALE",
                            f"rescale {self.parallelism} -> {int(want)}: "
                            "draining the set behind a final checkpoint",
                            data={"from": self.parallelism, "to": int(want)})
                self.stopping_epoch = self.next_epoch
                self.next_epoch += 1
                self._trigger_checkpoint(self.stopping_epoch, then_stop=True)
                self._set_state(JobState.RESCALING)
                return
            if want and int(want) == self.parallelism:
                # no-op rescale: clear the request
                self.db.update_job(self.job_id, desired_parallelism=None)

        # live evolution requests from the API (versioned redeploy,
        # `POST /pipelines/<id>/evolve`): drain the running (v1) set behind
        # a final checkpoint; _finish_evolve then proves the carry-over
        # with the plan-diff pass and reschedules the evolved plan from
        # exactly that checkpoint
        if self.state == JobState.RUNNING and not desired_stop:
            want_sql = job.get("desired_query")
            if want_sql and want_sql != self.sql:
                self.evolve_to = want_sql
                self._event("INFO", "JOB_EVOLVE_STARTED",
                            "evolution accepted: draining the running set "
                            "behind a final checkpoint before the "
                            "versioned redeploy",
                            data={"drain_epoch": self.next_epoch})
                self.stopping_epoch = self.next_epoch
                self.next_epoch += 1
                self._trigger_checkpoint(self.stopping_epoch,
                                         then_stop=True)
                self._set_state(JobState.EVOLVING)
                return
            if want_sql and want_sql == self.sql:
                # no-op evolution: clear the request
                self.db.clear_desired_query(self.job_id, want_sql)

        # stop requests from the API; a stop also voids any pending rescale
        # so it cannot resurrect as a surprise drain cycle at a later restart
        if self.state == JobState.RUNNING and desired_stop:
            if desired_stop == "checkpoint":
                self.stopping_epoch = self.next_epoch
                self.next_epoch += 1
                self._trigger_checkpoint(self.stopping_epoch, then_stop=True)
                self._set_state(JobState.CHECKPOINT_STOPPING, desired_parallelism=None)
            else:
                for h in self.handles:
                    if h is not None:
                        h.stop()
                self._set_state(JobState.STOPPING, desired_parallelism=None)
            return

        # periodic checkpoints (reference default-checkpoint-interval)
        if self.state == JobState.RUNNING:
            interval = cfgv.get("checkpoint.interval-ms") / 1000
            if time.monotonic() - self.last_checkpoint_time >= interval:
                self._trigger_checkpoint(self.next_epoch)
                self.next_epoch += 1
                self.last_checkpoint_time = time.monotonic()


class ControllerServer:
    """Polls the DB and supervises every live job
    (reference ControllerServer + start_updater)."""

    def __init__(self, db: Database, scheduler: Optional[Scheduler] = None,
                 storage_url: Optional[str] = None, poll_interval: float = 0.1):
        self.db = db
        self.scheduler = scheduler or scheduler_for(
            config().get("controller.scheduler"), db)
        self.storage_url = storage_url
        self.poll_interval = poll_interval
        # concurrency: single-writer — mutated only inside tick(), which runs either on the controller thread (start()) or inline in tests, never both; stop() reads after joining the thread
        self.jobs: dict[str, JobController] = {}
        # the multi-tenant fleet: one shared slot pool / admission queue
        # across every job this controller supervises
        self.fleet = FleetManager(self.scheduler)
        # per-job tick isolation: a job whose supervision step overruns
        # fleet.tick-budget-ms is deprioritized (runs last, skipped for
        # up to tick-penalty-max ticks) so a melting job cannot starve
        # its neighbors' heartbeat/watchdog checks — but it always runs
        # again, never skipped forever
        self._tick_penalty: dict[str, int] = {}  # concurrency: single-writer — tick()-private (see jobs above)
        self._tick_skip: dict[str, int] = {}  # concurrency: single-writer — tick()-private (see jobs above)
        self._overrun_emitted: dict[str, float] = {}  # concurrency: single-writer — tick()-private (see jobs above)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ControllerServer":
        self._thread = threading.Thread(target=self._run, daemon=True, name="controller")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.poll_interval)

    # supervision states the per-job tick budget applies to: compile and
    # schedule steps are EXPECTED to be slow (planning, spawning worker
    # sets) — the isolation target is a melting RUNNING job stalling its
    # neighbors' heartbeat/watchdog checks
    _BUDGETED_STATES = (JobState.RUNNING, JobState.CHECKPOINT_STOPPING,
                        JobState.STOPPING, JobState.FINISHING,
                        JobState.RESCALING, JobState.EVOLVING)

    def tick(self) -> None:
        for row in self.db.list_jobs():
            jid = row["id"]
            if jid not in self.jobs:
                if row["state"] in ("Failed", "Finished", "Stopped"):
                    continue
                self.jobs[jid] = JobController(
                    self.db, jid, self.scheduler, self.storage_url,
                    fleet=self.fleet,
                )
        for jid, jc in list(self.jobs.items()):
            if jc.is_terminal():
                # persist a final snapshot, then free the process-global
                # registry (it would otherwise grow per finished job)
                from ..metrics import registry as metrics_registry

                final = metrics_registry.job_metrics(jid)
                if final:
                    self.db.record_metrics(jid, final)
                    from ..obs.profile import job_profile

                    self.db.record_profile(jid, job_profile(final))
                metrics_registry.clear_job(jid)
                # flush every buffered epoch trace to the DB (postmortems
                # via the API/`trace` CLI survive the recorder eviction)
                for epoch in obs_trace.recorder.epochs(jid):
                    self.db.record_trace(
                        jid, epoch, obs_trace.recorder.events(jid, epoch))
                obs_trace.recorder.clear_job(jid)
                # job event feed: final flush, then free the ring (the DB
                # copy is the postmortem surface)
                jc._flush_events()
                events_recorder.clear_job(jid)
                # freed capacity is handed out by this tick's admission
                # pass below — any terminal job triggers re-admission
                self.fleet.release(jid)
                self._tick_penalty.pop(jid, None)
                self._tick_skip.pop(jid, None)
                self._overrun_emitted.pop(jid, None)
                del self.jobs[jid]
                continue
        # fleet pass BEFORE job steps: capacity refresh, quota-preemption
        # marks, the DRR admission pass over freshly freed slots, the
        # fleet autoscaler, gauge export, and the persisted snapshot
        self.fleet.tick(self.db)
        budget_ms = float(config().get("fleet.tick-budget-ms") or 0)
        pen_max = max(1, int(config().get("fleet.tick-penalty-max") or 4))
        # deprioritized jobs run LAST so a melting job's slow step lands
        # after its neighbors already got their heartbeat/watchdog ticks
        ordered = sorted(self.jobs.items(),
                         key=lambda kv: self._tick_penalty.get(kv[0], 0))
        for jid, jc in ordered:
            if jc.is_terminal():
                continue  # cleaned up at the top of the next tick
            skip = self._tick_skip.get(jid, 0)
            if skip > 0:
                self._tick_skip[jid] = skip - 1
                continue
            budgeted = budget_ms > 0 and jc.state in self._BUDGETED_STATES
            t0 = time.monotonic()
            jc.step()
            dt_ms = (time.monotonic() - t0) * 1000.0
            if budgeted and dt_ms > budget_ms:
                pen = min(self._tick_penalty.get(jid, 0) + 1, pen_max)
                self._tick_penalty[jid] = pen
                self._tick_skip[jid] = pen
                now = time.monotonic()
                if now - self._overrun_emitted.get(jid, 0.0) >= 5.0:
                    self._overrun_emitted[jid] = now
                    jc._event(
                        "WARN", "JOB_TICK_OVERRUN",
                        f"supervision step took {dt_ms:.0f}ms (budget "
                        f"{budget_ms:.0f}ms); deprioritized for {pen} "
                        "ticks — neighbors tick first, this job still "
                        "ticks every cycle after that",
                        data={"ms": round(dt_ms, 1),
                              "budget_ms": budget_ms, "penalty": pen})
            elif self._tick_penalty.get(jid):
                # a compliant step decays the penalty toward zero
                pen = self._tick_penalty[jid] - 1
                if pen:
                    self._tick_penalty[jid] = pen
                else:
                    self._tick_penalty.pop(jid, None)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        for jc in self.jobs.values():
            jc._kill_all()

    def wait_for_state(self, job_id: str, *states: str, timeout: float = 120) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.db.get_job(job_id)
            if job and job["state"] in states:
                return job["state"]
            if job and job["state"] == "Failed" and "Failed" not in states:
                raise RuntimeError(f"job failed: {job['failure_message']}")
            time.sleep(0.05)
        raise TimeoutError(f"job {job_id} never reached {states}")
