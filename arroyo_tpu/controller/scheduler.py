"""Schedulers: how a job's dataflow gets executed.

Reference: crates/arroyo-controller/src/schedulers/mod.rs:43-62 (trait
Scheduler). All four reference schedulers are implemented against the same
WorkerHandle contract: EmbeddedScheduler (in-process tasks for the run
CLI), ProcessScheduler (worker subprocesses), NodeScheduler (placement on
registered node daemons, this module), and KubernetesScheduler (one worker
pod per job, controller/kube.py).

Pipelines are defined by SQL text; workers re-plan locally, so no live
expression objects cross the process boundary (the reference ships protobuf
physical plans instead — same idea, the plan is data).

Worker wire protocol (process scheduler), JSON lines:
  worker -> controller (stdout): {"event": "started", "dp_port": P?} |
      {"event": "heartbeat"} | {"event": "checkpoint_completed", "epoch": N} |
      {"event": "subtask_acked", "epoch": N, "node": id, "subtask": S} |
      {"event": "subtask_finished", "node": id, "subtask": S} |
      {"event": "finished"} | {"event": "failed", "error": "..."}
  controller -> worker (stdin): {"cmd": "checkpoint", "epoch": N,
      "then_stop": bool} | {"cmd": "stop"} | {"cmd": "commit", "epoch": N} |
      {"cmd": "peers", "peers": {"0": [host, port], ...}}
This plays the role of the reference's ControllerGrpc/WorkerGrpc services
(proto/rpc.proto:185-202, :397-410). The subtask_acked/commit/peers legs
exist for multi-worker jobs (start_workers): workers under an assignment
relay checkpoint acks to the controller's CheckpointCoordinator and only
finalize phase 2 on an injected commit (checkpoint_state.py).
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import sys
import threading
import time
from typing import Optional


class PlacementFull(RuntimeError):
    """The cluster has no free capacity for a placement (node-daemon 409,
    no daemon with free slots, or no daemons registered at all). The
    controller treats this as retriable: the job re-queues into the fleet's
    admission queue with deterministic backoff — it is never failed and
    never burns a restart-budget token."""


class WorkerHandle:
    """One running worker of a job (a job's dataflow runs on one or more)."""

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def poll_events(self) -> list[dict]:
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    def last_heartbeat(self) -> float:
        raise NotImplementedError

    def send_commit(self, epoch: int) -> None:
        """Phase-2 commit injection (multi-worker 2PC): only ever called
        after the epoch's job-level metadata is durable across all workers."""
        raise NotImplementedError


class EmbeddedWorkerHandle(WorkerHandle):
    """Runs the Engine inside the controller process
    (reference schedulers/embedded.rs)."""

    def __init__(self, sql: str, job_id: str, parallelism: int,
                 restore_epoch: Optional[int], storage_url: Optional[str] = None,
                 graph_json: Optional[str] = None, engine=None):
        from ..engine.engine import Engine

        if engine is not None:
            # multi-worker set: EmbeddedScheduler.start_workers pre-built the
            # engine with its assignment/worker_index/network wiring
            self.engine = engine
        else:
            if graph_json is not None:
                from ..graph import Graph

                graph = Graph.loads(graph_json)  # pre-planned, pre-parallelized IR
            else:
                from ..sql import plan_query
                from ..sql.planner import set_parallelism

                pp = plan_query(sql)
                if parallelism > 1:
                    set_parallelism(pp.graph, parallelism)
                graph = pp.graph
            self.engine = Engine(graph, job_id=job_id, restore_epoch=restore_epoch,
                                 storage_url=storage_url)
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._reported_epochs: set[int] = set()
        # _emit_epochs runs on BOTH the worker thread (_run) and the
        # controller thread (poll_events): without the lock two concurrent
        # emits can both compute the completed-minus-reported difference
        # before either records it, double-reporting an epoch
        self._emit_lock = threading.Lock()
        self._done = False  # concurrency: single-writer — monotonic done flag; set once by the worker thread, stale reads just delay done-detection one poll
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            self._events.put({"event": "started"})
            self.engine.run_to_completion(timeout=None)
            self._emit_epochs()
            if self.engine._aborted:
                # an externally-killed engine's aborted tasks still drain the
                # done-accounting; reporting "finished" here would make the
                # controller wait on the rest of the worker set forever
                # instead of restoring it
                self._events.put({"event": "failed",
                                  "error": "worker aborted (killed)"})
            else:
                self._events.put({"event": "finished"})
        except Exception as e:  # noqa: BLE001 - worker failure is data
            self._emit_epochs()
            self._events.put({"event": "failed", "error": str(e)})
        finally:
            self._done = True

    def _emit_epochs(self) -> None:
        if self.engine.coordinated:
            # multi-worker: relay per-subtask acks upward; the controller's
            # CheckpointCoordinator (not this worker) declares epochs done
            while True:
                try:
                    self._events.put(self.engine.coordinator_events.get_nowait())
                except queue.Empty:
                    break
        else:
            with self._emit_lock:
                for ep in sorted(
                        self.engine._completed_epochs - self._reported_epochs):
                    self._reported_epochs.add(ep)
                    self._events.put(
                        {"event": "checkpoint_completed", "epoch": ep})
        from ..connectors.preview import take_preview_rows

        lines = take_preview_rows(self.engine.job_id)
        if lines:
            self._events.put({"event": "sink_data", "lines": lines})
        now = time.monotonic()
        with self._emit_lock:
            due = now - getattr(self, "_last_metrics", 0.0) >= 1.0
            if due:
                self._last_metrics = now
        if due:
            from ..metrics import registry as _mreg

            self._events.put({
                "event": "metrics",
                "data": _mreg.job_metrics(self.engine.job_id),
            })

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        self.engine.trigger_checkpoint(epoch, then_stop=then_stop)

    def stop(self) -> None:
        self.engine.stop()

    def kill(self) -> None:
        self.engine._abort()
        if self.engine.network is not None:
            # multi-worker set teardown / post-finish cleanup: release the
            # data-plane listener and outgoing connections
            self.engine.network.close()

    def poll_events(self) -> list[dict]:
        self._emit_epochs()
        out = []
        while True:
            try:
                out.append(self._events.get_nowait())
            except queue.Empty:
                return out

    def alive(self) -> bool:
        return not self._done

    def last_heartbeat(self) -> float:
        # actual engine progress, not mere thread existence: a wedged
        # in-process engine (task hung in an operator or a stalled storage
        # call) must still trip the controller's heartbeat timeout
        if self._done:
            return time.monotonic()  # exit/failure is reported via events
        return self.engine.heartbeat()

    def send_commit(self, epoch: int) -> None:
        self.engine.deliver_commit(epoch)


class ProcessWorkerHandle(WorkerHandle):
    """Spawns `python -m arroyo_tpu worker` (reference ProcessScheduler,
    schedulers/mod.rs:72: spawns `arroyo worker` with env-injected config)."""

    def __init__(self, sql: str, job_id: str, parallelism: int,
                 restore_epoch: Optional[int], storage_url: Optional[str] = None,
                 udf_specs: Optional[list] = None, graph_json: Optional[str] = None,
                 worker_index: Optional[int] = None, n_workers: int = 1,
                 assignment: Optional[list] = None, dp_bind: Optional[str] = None):
        import tempfile

        # the planned IR ships as data when serializable (reference:
        # StartExecutionReq carries the protobuf program); SQL remains the
        # fallback for graphs holding live objects
        suffix, payload, flag = (
            (".graph.json", graph_json, "--graph-file") if graph_json is not None
            else (".sql", sql, "--sql-file")
        )
        self._sql_file = tempfile.NamedTemporaryFile(
            "w", suffix=suffix, prefix=f"{job_id}-", delete=False
        )
        self._sql_file.write(payload)
        self._sql_file.close()
        cmd = [
            sys.executable, "-m", "arroyo_tpu", "worker",
            flag, self._sql_file.name,
            "--job-id", job_id,
            "--parallelism", str(parallelism),
        ]
        if restore_epoch is not None:
            cmd += ["--restore-epoch", str(restore_epoch)]
        if storage_url:
            cmd += ["--storage-url", storage_url]
        self._assignment_file: Optional[str] = None
        if n_workers > 1:
            # assignment ships as a temp file: [[node_id, subtask, worker]...]
            af = tempfile.NamedTemporaryFile(
                "w", suffix=".json", prefix=f"{job_id}-assign-", delete=False
            )
            json.dump(assignment or [], af)
            af.close()
            self._assignment_file = af.name
            cmd += ["--worker-index", str(worker_index or 0),
                    "--n-workers", str(n_workers),
                    "--assignment-file", af.name]
            if dp_bind:
                cmd += ["--dp-bind", dp_bind]
        self._udfs_file: Optional[str] = None
        if udf_specs:
            uf = tempfile.NamedTemporaryFile(
                "w", suffix=".json", prefix=f"{job_id}-udfs-", delete=False
            )
            json.dump(udf_specs, uf)
            uf.close()
            self._udfs_file = uf.name
            cmd += ["--udfs-file", uf.name]
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1,
        )
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._hb = time.monotonic()
        self.dp_port: Optional[int] = None  # data-plane port (multi-worker)
        self._started = threading.Event()
        self._reader = threading.Thread(target=self._read_stdout, daemon=True)
        self._reader.start()

    def _read_stdout(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # worker debug output
            self._hb = time.monotonic()
            if ev.get("event") == "started":
                if ev.get("dp_port") is not None:
                    self.dp_port = int(ev["dp_port"])
                self._started.set()
            if ev.get("event") != "heartbeat":
                self._events.put(ev)
        rc = self.proc.wait()
        self._started.set()  # unblock wait_dp_port on a crashed spawn
        if rc != 0:
            err = self.proc.stderr.read() if self.proc.stderr else ""
            self._events.put({"event": "failed", "error": f"worker exited {rc}: {err[-2000:]}"})

    def wait_dp_port(self, timeout: float = 60.0) -> Optional[int]:
        """Block until the worker reported its data-plane port (multi-worker
        peer exchange); None if it died or never reported."""
        self._started.wait(timeout)
        return self.dp_port

    def _send(self, obj: dict) -> None:
        if self.proc.stdin and self.proc.poll() is None:
            try:
                self.proc.stdin.write(json.dumps(obj) + "\n")
                self.proc.stdin.flush()
            except (BrokenPipeError, OSError):
                pass

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        self._send({"cmd": "checkpoint", "epoch": epoch, "then_stop": then_stop})

    def stop(self) -> None:
        self._send({"cmd": "stop"})

    def send_commit(self, epoch: int) -> None:
        self._send({"cmd": "commit", "epoch": epoch})

    def send_peers(self, peers: dict[int, tuple[str, int]]) -> None:
        self._send({"cmd": "peers",
                    "peers": {str(k): list(v) for k, v in peers.items()}})

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        for path in (self._sql_file.name, self._udfs_file, self._assignment_file):
            if path:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def poll_events(self) -> list[dict]:
        out = []
        while True:
            try:
                out.append(self._events.get_nowait())
            except queue.Empty:
                return out

    def alive(self) -> bool:
        return self.proc.poll() is None or not self._events.empty()

    def last_heartbeat(self) -> float:
        return self._hb


class Scheduler:
    """reference trait Scheduler (schedulers/mod.rs:43-62)."""

    def start_worker(self, sql: str, job_id: str, parallelism: int,
                     restore_epoch: Optional[int],
                     storage_url: Optional[str] = None,
                     udf_specs: Optional[list] = None,
                     graph_json: Optional[str] = None) -> WorkerHandle:
        raise NotImplementedError

    def start_workers(self, sql: str, job_id: str, parallelism: int,
                      restore_epoch: Optional[int],
                      storage_url: Optional[str] = None,
                      udf_specs: Optional[list] = None,
                      graph_json: Optional[str] = None,
                      n_workers: int = 1) -> list[WorkerHandle]:
        """Launch the job's worker set. The default keeps one worker per
        job (the kubernetes scheduler's current shape: one pod holds the
        whole dataflow); Embedded/Process/Node override with real
        multi-worker placement under a computed subtask assignment.
        Multi-worker needs the pre-planned IR; without graph_json the set
        degrades to a single worker rather than re-planning per worker."""
        return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                  storage_url, udf_specs, graph_json)]

    def provision_slots(self, target: int) -> Optional[int]:
        """Fleet-elasticity hook (controller/fleet.py): asked to resize
        the worker pool to ``target`` slots. Schedulers whose pool is a
        synthetic budget (embedded/process) return the accepted size; a
        scheduler whose pool is sized externally (node daemons joining a
        cluster, a kubernetes node pool) returns None — the fleet then
        only moves the ``arroyo_fleet_target_workers`` gauge, which is
        the knob an external node-pool autoscaler actuates."""
        return None


class EmbeddedScheduler(Scheduler):
    def provision_slots(self, target):
        # synthetic pool: in-process workers have no physical node budget,
        # so the fleet's resize is accepted as-is
        return int(target)

    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None):
        if udf_specs:
            from ..compiler import activate_udf_specs

            activate_udf_specs(udf_specs)
        return EmbeddedWorkerHandle(sql, job_id, parallelism, restore_epoch, storage_url,
                                    graph_json)

    def start_workers(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                      udf_specs=None, graph_json=None, n_workers=1):
        if n_workers <= 1 or graph_json is None:
            return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                      storage_url, udf_specs, graph_json)]
        from ..engine.engine import Engine
        from ..engine.network import NetworkManager
        from ..graph import Graph
        from .checkpoint_state import compute_assignment

        if udf_specs:
            from ..compiler import activate_udf_specs

            activate_udf_specs(udf_specs)
        assignment, _expected, n = compute_assignment(graph_json, n_workers)
        if n <= 1:
            return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                      storage_url, udf_specs, graph_json)]
        # ports are known at NetworkManager construction, so peers can be
        # wired before any engine starts sending
        managers = [NetworkManager() for _ in range(n)]
        peers = {i: ("127.0.0.1", m.port) for i, m in enumerate(managers)}
        handles = []
        for i, m in enumerate(managers):
            m.set_peers(peers)
            eng = Engine(Graph.loads(graph_json), job_id=job_id,
                         restore_epoch=restore_epoch, storage_url=storage_url,
                         assignment=assignment, worker_index=i, network=m)
            handles.append(EmbeddedWorkerHandle(
                sql, job_id, parallelism, restore_epoch, storage_url,
                engine=eng))
        return handles


class ProcessScheduler(Scheduler):
    def provision_slots(self, target):
        # synthetic pool (subprocesses on one machine): accepted as-is
        return int(target)

    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None):
        return ProcessWorkerHandle(sql, job_id, parallelism, restore_epoch, storage_url,
                                   udf_specs, graph_json)

    def start_workers(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                      udf_specs=None, graph_json=None, n_workers=1):
        if n_workers <= 1 or graph_json is None:
            return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                      storage_url, udf_specs, graph_json)]
        from .checkpoint_state import compute_assignment

        assignment, _expected, n = compute_assignment(graph_json, n_workers)
        if n <= 1:
            return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                      storage_url, udf_specs, graph_json)]
        assign_json = [[nid, sub, w] for (nid, sub), w in sorted(assignment.items())]
        handles = [
            ProcessWorkerHandle(sql, job_id, parallelism, restore_epoch,
                                storage_url, udf_specs, graph_json,
                                worker_index=i, n_workers=n,
                                assignment=assign_json, dp_bind="127.0.0.1")
            for i in range(n)
        ]
        # peer exchange: every worker binds its data plane and reports the
        # port in its "started" event; engines only start once all peers
        # are known (the worker holds task startup until the peers cmd)
        peers: dict[int, tuple[str, int]] = {}
        for i, h in enumerate(handles):
            port = h.wait_dp_port(timeout=90.0)
            if port is None:
                for hh in handles:
                    hh.kill()
                raise RuntimeError(
                    f"worker {i}/{n} of job {job_id} never reported its "
                    "data-plane port (died during startup?)")
            peers[i] = ("127.0.0.1", port)
        for h in handles:
            h.send_peers(peers)
        return handles


class NodeWorkerHandle(WorkerHandle):
    """Controller-side proxy for a worker running under a remote node
    daemon (reference NodeScheduler, schedulers/mod.rs:316): commands go
    over the node's HTTP surface; events and liveness are polled."""

    def __init__(self, node_addr: str, sql: str, job_id: str, parallelism: int,
                 restore_epoch, storage_url, udf_specs, graph_json=None,
                 worker_index=None, n_workers=1, assignment=None, dp_bind=None):
        from .node import _get, _post

        self._get, self._post = _get, _post
        self.node_addr = node_addr.rstrip("/")
        body = {
            "sql": sql, "job_id": job_id, "parallelism": parallelism,
            "restore_epoch": restore_epoch, "storage_url": storage_url,
            "udf_specs": udf_specs, "graph_json": graph_json,
        }
        if n_workers > 1:
            body.update({"worker_index": worker_index, "n_workers": n_workers,
                         "assignment": assignment,
                         # bind all interfaces: data-plane peers dial in
                         # from other machines of the cluster
                         "dp_bind": dp_bind or "0.0.0.0"})
        r = _post(f"{self.node_addr}/start_worker", body)
        self.worker_id = r["worker_id"]
        self._alive = True
        self._hb = time.monotonic()
        self._buffer: list[dict] = []
        self.dp_port: Optional[int] = None

    def _command(self, path: str, body: dict) -> None:
        """Controller -> node-daemon command with the controller_rpc chaos
        site (drop/dup/delay model a flaky control network; a dropped
        command is recovered by protocol-level retries — the stuck-epoch
        watchdog re-triggers, commits re-deliver cumulatively — never by
        pretending it arrived)."""
        from ..faults import fault_point

        verdict = fault_point("controller_rpc", key=path, op="post")
        if verdict is not None and verdict[0] == "drop":
            return
        try:
            self._post(f"{self.node_addr}{path}", body)
            if verdict is not None and verdict[0] == "dup":
                self._post(f"{self.node_addr}{path}", body)
        except OSError:
            pass

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        self._command(f"/workers/{self.worker_id}/send",
                      {"cmd": "checkpoint", "epoch": epoch, "then_stop": then_stop})

    def stop(self) -> None:
        self._command(f"/workers/{self.worker_id}/stop", {})

    def send_commit(self, epoch: int) -> None:
        self._command(f"/workers/{self.worker_id}/send",
                      {"cmd": "commit", "epoch": epoch})

    def send_peers(self, peers: dict[int, tuple[str, int]]) -> None:
        self._command(f"/workers/{self.worker_id}/send",
                      {"cmd": "peers",
                       "peers": {str(k): list(v) for k, v in peers.items()}})

    def kill(self) -> None:
        try:
            self._post(f"{self.node_addr}/workers/{self.worker_id}/kill", {})
        except OSError:
            pass
        self._alive = False

    def poll_events(self) -> list[dict]:
        from ..faults import fault_point

        out, self._buffer = self._buffer, []
        verdict = fault_point("controller_rpc",
                              key=f"/workers/{self.worker_id}/events", op="get")
        if verdict is not None and verdict[0] == "drop":
            # a dropped poll loses nothing: the daemon only drains its
            # buffer when a poll actually arrives, so the next one catches up
            return out
        try:
            r = self._get(f"{self.node_addr}/workers/{self.worker_id}/events")
        except OSError:
            # node unreachable: let the heartbeat timeout declare death
            return out
        # anchor to the WORKER's own heartbeat (relayed as an age so clocks
        # need not agree): a hung worker must still trip the controller's
        # heartbeat timeout even though the node daemon answers polls
        self._hb = time.monotonic() - float(r.get("hb_age_s", 0.0))
        self._alive = bool(r["alive"]) or bool(r["events"])
        for ev in r["events"]:
            if ev.get("event") == "started" and ev.get("dp_port") is not None:
                self.dp_port = int(ev["dp_port"])
        return out + r["events"]

    def wait_dp_port(self, timeout: float = 90.0) -> Optional[int]:
        """Poll the node daemon until the worker reports its data-plane
        port; events seen along the way are buffered for poll_events."""
        deadline = time.monotonic() + timeout
        while self.dp_port is None and time.monotonic() < deadline:
            try:
                r = self._get(f"{self.node_addr}/workers/{self.worker_id}/events")
            except OSError:
                r = None  # daemon briefly unreachable; re-poll below
            if r is None:
                time.sleep(0.2)
                continue
            self._hb = time.monotonic() - float(r.get("hb_age_s", 0.0))
            for ev in r["events"]:
                if ev.get("event") == "started" and ev.get("dp_port") is not None:
                    self.dp_port = int(ev["dp_port"])
                self._buffer.append(ev)
            if not r["alive"] and not r["events"]:
                return None
            if self.dp_port is None:
                time.sleep(0.1)
        return self.dp_port

    def alive(self) -> bool:
        return self._alive

    def last_heartbeat(self) -> float:
        return self._hb


class LazyNodeWorkerHandle(WorkerHandle):
    """Deferred placement on a node daemon. The controller's supervision
    loop is single-threaded, so start_worker must not block while the
    cluster is briefly full or a daemon is mid-restart: this handle retries
    placement from poll_events (same shape as KubernetesWorkerHandle) and
    queues control commands issued before placement lands."""

    def __init__(self, sched: "NodeScheduler", args: tuple,
                 placement_timeout_s: float):
        self._sched = sched
        self._args = args
        self._deadline = time.monotonic() + placement_timeout_s
        self._inner: Optional[NodeWorkerHandle] = None
        self._queued: list[tuple] = []
        self._dead = False
        self._last = "no live node daemons registered"

    def _try_place(self) -> Optional[list[dict]]:
        inner, self._last = self._sched._place_once(self._args, self._last)
        if inner is not None:
            self._inner = inner
            for cmd in self._queued:
                getattr(inner, cmd[0])(*cmd[1:])
            self._queued.clear()
            return None
        if time.monotonic() > self._deadline:
            self._dead = True
            return [{"event": "failed", "error": f"placement failed: {self._last}"}]
        return None

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        if self._inner is None:
            self._queued.append(("trigger_checkpoint", epoch, then_stop))
        else:
            self._inner.trigger_checkpoint(epoch, then_stop)

    def stop(self) -> None:
        if self._inner is None:
            self._queued.append(("stop",))
        else:
            self._inner.stop()

    def send_commit(self, epoch: int) -> None:
        if self._inner is None:
            self._queued.append(("send_commit", epoch))
        else:
            self._inner.send_commit(epoch)

    def kill(self) -> None:
        self._dead = True
        if self._inner is not None:
            self._inner.kill()

    def poll_events(self) -> list[dict]:
        if self._dead:
            return []
        if self._inner is None:
            return self._try_place() or []
        return self._inner.poll_events()

    def alive(self) -> bool:
        if self._dead:
            return False
        return True if self._inner is None else self._inner.alive()

    def last_heartbeat(self) -> float:
        if self._inner is None:
            return time.monotonic()  # placement has its own deadline
        return self._inner.last_heartbeat()


class NodeScheduler(Scheduler):
    """Places workers on registered node daemons (least-loaded first)."""

    def __init__(self, db):
        self.db = db

    def _place_once(self, args: tuple, last: str, **multi_kw):
        """One placement sweep over live daemons -> (handle|None, reason).
        A 409 (the daemon's hard slot limit — its status poll races other
        placements) reads as a capacity rejection, which the controller
        answers by re-queueing the job into the fleet's admission queue
        with backoff, never by failing it."""
        import urllib.error

        from ..faults import InjectedFault, fault_point
        from .node import _get

        nodes = self.db.list_nodes(alive_within_s=10.0)
        candidates = []
        for n in nodes:
            try:
                st = _get(f"{n['addr']}/status", timeout=5.0)
            except OSError:
                continue
            free = int(st["slots"]) - int(st["used"])
            if free >= 1:
                candidates.append((free, n))
        candidates.sort(key=lambda fn: -fn[0])
        for _free, n in candidates:
            try:
                # chaos site `admission`: a node 409 (or a slow admission
                # RPC) at the exact placement moment — fail models the
                # daemon rejecting after the status poll said free
                fault_point("admission", key=str(n["id"]),
                            job=str(args[1]) if len(args) > 1 else "")
                return NodeWorkerHandle(n["addr"], *args, **multi_kw), last
            except InjectedFault:
                last = f"node {n['id']} full (409, injected)"
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    last = f"node {n['id']} full (409)"
                else:
                    last = f"node {n['id']} rejected placement: {e}"
            except OSError as e:
                last = f"node {n['id']} unreachable: {e}"
        if nodes and not candidates:
            last = "no node daemon with free slots"
        return None, last

    @staticmethod
    def _capacity_reason(last: str) -> bool:
        return ("full (409" in last or "free slots" in last
                or "no live node daemons" in last)

    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None,
                     placement_timeout_s: float = 30.0):
        args = (sql, job_id, parallelism, restore_epoch, storage_url,
                udf_specs, graph_json)
        # fast path: place immediately when capacity exists, so the common
        # case still fails fast on hard errors and tests see a live handle
        handle, last = self._place_once(args, "no live node daemons registered")
        if handle is not None:
            return handle
        lazy = LazyNodeWorkerHandle(self, args, placement_timeout_s)
        lazy._last = last
        return lazy

    def start_workers(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                      udf_specs=None, graph_json=None, n_workers=1,
                      placement_timeout_s: float = 30.0):
        if n_workers <= 1 or graph_json is None:
            return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                      storage_url, udf_specs, graph_json,
                                      placement_timeout_s)]
        from urllib.parse import urlparse

        from .checkpoint_state import compute_assignment

        assignment, _expected, n = compute_assignment(graph_json, n_workers)
        if n <= 1:
            return [self.start_worker(sql, job_id, parallelism, restore_epoch,
                                      storage_url, udf_specs, graph_json,
                                      placement_timeout_s)]
        assign_json = [[nid, sub, w] for (nid, sub), w in sorted(assignment.items())]
        # worker-set placement is all-or-nothing and synchronous: the data
        # plane needs every peer's (host, port) before any engine may run,
        # so lazy placement cannot apply here. A partially placed set is
        # torn down rather than left half-running.
        handles: list[NodeWorkerHandle] = []
        deadline = time.monotonic() + placement_timeout_s
        last = "no live node daemons registered"
        try:
            for i in range(n):
                args = (sql, job_id, parallelism, restore_epoch, storage_url,
                        udf_specs, graph_json)
                while True:
                    h, last = self._place_once(
                        args, last, worker_index=i, n_workers=n,
                        assignment=assign_json)
                    if h is not None:
                        handles.append(h)
                        break
                    if time.monotonic() > deadline:
                        if self._capacity_reason(last):
                            # capacity, not a hard error: the controller
                            # re-queues the job instead of failing it
                            raise PlacementFull(
                                f"placed {i}/{n} workers of job {job_id}: "
                                f"{last}")
                        raise RuntimeError(
                            f"placed {i}/{n} workers of job {job_id}: {last}")
                    time.sleep(0.25)
            peers: dict[int, tuple[str, int]] = {}
            for i, h in enumerate(handles):
                port = h.wait_dp_port(timeout=90.0)
                if port is None:
                    raise RuntimeError(
                        f"worker {i}/{n} of job {job_id} never reported its "
                        "data-plane port")
                peers[i] = (urlparse(h.node_addr).hostname or "127.0.0.1", port)
            for h in handles:
                h.send_peers(peers)
        except Exception:
            for h in handles:
                h.kill()
            raise
        return handles


def scheduler_for(name: str, db=None) -> Scheduler:
    if name == "embedded":
        return EmbeddedScheduler()
    if name == "process":
        return ProcessScheduler()
    if name == "node":
        if db is None:
            raise ValueError("node scheduler needs the shared database")
        return NodeScheduler(db)
    if name == "kubernetes":
        if db is None:
            raise ValueError("kubernetes scheduler needs the shared database")
        from .kube import KubernetesScheduler

        return KubernetesScheduler(db)
    raise ValueError(
        f"unknown scheduler {name!r} (have: embedded, process, node, kubernetes)")
