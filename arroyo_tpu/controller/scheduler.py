"""Schedulers: how a job's dataflow gets executed.

Reference: crates/arroyo-controller/src/schedulers/mod.rs:43-62 (trait
Scheduler). All four reference schedulers are implemented against the same
WorkerHandle contract: EmbeddedScheduler (in-process tasks for the run
CLI), ProcessScheduler (worker subprocesses), NodeScheduler (placement on
registered node daemons, this module), and KubernetesScheduler (one worker
pod per job, controller/kube.py).

Pipelines are defined by SQL text; workers re-plan locally, so no live
expression objects cross the process boundary (the reference ships protobuf
physical plans instead — same idea, the plan is data).

Worker wire protocol (process scheduler), JSON lines:
  worker -> controller (stdout): {"event": "started" | "heartbeat" |
      "checkpoint_completed", "epoch": N} | {"event": "finished"} |
      {"event": "failed", "error": "..."}
  controller -> worker (stdin): {"cmd": "checkpoint", "epoch": N,
      "then_stop": bool} | {"cmd": "stop"}
This plays the role of the reference's ControllerGrpc/WorkerGrpc services
(proto/rpc.proto:185-202, :397-410).
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import sys
import threading
import time
from typing import Optional


class WorkerHandle:
    """One running execution of a job's dataflow."""

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def poll_events(self) -> list[dict]:
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    def last_heartbeat(self) -> float:
        raise NotImplementedError


class EmbeddedWorkerHandle(WorkerHandle):
    """Runs the Engine inside the controller process
    (reference schedulers/embedded.rs)."""

    def __init__(self, sql: str, job_id: str, parallelism: int,
                 restore_epoch: Optional[int], storage_url: Optional[str] = None,
                 graph_json: Optional[str] = None):
        from ..engine.engine import Engine

        if graph_json is not None:
            from ..graph import Graph

            graph = Graph.loads(graph_json)  # pre-planned, pre-parallelized IR
        else:
            from ..sql import plan_query
            from ..sql.planner import set_parallelism

            pp = plan_query(sql)
            if parallelism > 1:
                set_parallelism(pp.graph, parallelism)
            graph = pp.graph
        self.engine = Engine(graph, job_id=job_id, restore_epoch=restore_epoch,
                             storage_url=storage_url)
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._reported_epochs: set[int] = set()
        self._done = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            self._events.put({"event": "started"})
            self.engine.run_to_completion(timeout=None)
            self._emit_epochs()
            self._events.put({"event": "finished"})
        except Exception as e:  # noqa: BLE001 - worker failure is data
            self._emit_epochs()
            self._events.put({"event": "failed", "error": str(e)})
        finally:
            self._done = True

    def _emit_epochs(self) -> None:
        for ep in sorted(self.engine._completed_epochs - self._reported_epochs):
            self._reported_epochs.add(ep)
            self._events.put({"event": "checkpoint_completed", "epoch": ep})
        from ..connectors.preview import take_preview_rows

        lines = take_preview_rows(self.engine.job_id)
        if lines:
            self._events.put({"event": "sink_data", "lines": lines})
        now = time.monotonic()
        if now - getattr(self, "_last_metrics", 0.0) >= 1.0:
            self._last_metrics = now
            from ..metrics import registry as _mreg

            self._events.put({
                "event": "metrics", "data": _mreg.job_metrics(self.engine.job_id)
            })

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        self.engine.trigger_checkpoint(epoch, then_stop=then_stop)

    def stop(self) -> None:
        self.engine.stop()

    def kill(self) -> None:
        self.engine._abort()

    def poll_events(self) -> list[dict]:
        self._emit_epochs()
        out = []
        while True:
            try:
                out.append(self._events.get_nowait())
            except queue.Empty:
                return out

    def alive(self) -> bool:
        return not self._done

    def last_heartbeat(self) -> float:
        return time.monotonic()  # in-process: liveness == thread state


class ProcessWorkerHandle(WorkerHandle):
    """Spawns `python -m arroyo_tpu worker` (reference ProcessScheduler,
    schedulers/mod.rs:72: spawns `arroyo worker` with env-injected config)."""

    def __init__(self, sql: str, job_id: str, parallelism: int,
                 restore_epoch: Optional[int], storage_url: Optional[str] = None,
                 udf_specs: Optional[list] = None, graph_json: Optional[str] = None):
        import tempfile

        # the planned IR ships as data when serializable (reference:
        # StartExecutionReq carries the protobuf program); SQL remains the
        # fallback for graphs holding live objects
        suffix, payload, flag = (
            (".graph.json", graph_json, "--graph-file") if graph_json is not None
            else (".sql", sql, "--sql-file")
        )
        self._sql_file = tempfile.NamedTemporaryFile(
            "w", suffix=suffix, prefix=f"{job_id}-", delete=False
        )
        self._sql_file.write(payload)
        self._sql_file.close()
        cmd = [
            sys.executable, "-m", "arroyo_tpu", "worker",
            flag, self._sql_file.name,
            "--job-id", job_id,
            "--parallelism", str(parallelism),
        ]
        if restore_epoch is not None:
            cmd += ["--restore-epoch", str(restore_epoch)]
        if storage_url:
            cmd += ["--storage-url", storage_url]
        self._udfs_file: Optional[str] = None
        if udf_specs:
            uf = tempfile.NamedTemporaryFile(
                "w", suffix=".json", prefix=f"{job_id}-udfs-", delete=False
            )
            json.dump(udf_specs, uf)
            uf.close()
            self._udfs_file = uf.name
            cmd += ["--udfs-file", uf.name]
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1,
        )
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._hb = time.monotonic()
        self._reader = threading.Thread(target=self._read_stdout, daemon=True)
        self._reader.start()

    def _read_stdout(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # worker debug output
            self._hb = time.monotonic()
            if ev.get("event") != "heartbeat":
                self._events.put(ev)
        rc = self.proc.wait()
        if rc != 0:
            err = self.proc.stderr.read() if self.proc.stderr else ""
            self._events.put({"event": "failed", "error": f"worker exited {rc}: {err[-2000:]}"})

    def _send(self, obj: dict) -> None:
        if self.proc.stdin and self.proc.poll() is None:
            try:
                self.proc.stdin.write(json.dumps(obj) + "\n")
                self.proc.stdin.flush()
            except (BrokenPipeError, OSError):
                pass

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        self._send({"cmd": "checkpoint", "epoch": epoch, "then_stop": then_stop})

    def stop(self) -> None:
        self._send({"cmd": "stop"})

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        for path in (self._sql_file.name, self._udfs_file):
            if path:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def poll_events(self) -> list[dict]:
        out = []
        while True:
            try:
                out.append(self._events.get_nowait())
            except queue.Empty:
                return out

    def alive(self) -> bool:
        return self.proc.poll() is None or not self._events.empty()

    def last_heartbeat(self) -> float:
        return self._hb


class Scheduler:
    """reference trait Scheduler (schedulers/mod.rs:43-62)."""

    def start_worker(self, sql: str, job_id: str, parallelism: int,
                     restore_epoch: Optional[int],
                     storage_url: Optional[str] = None,
                     udf_specs: Optional[list] = None,
                     graph_json: Optional[str] = None) -> WorkerHandle:
        raise NotImplementedError


class EmbeddedScheduler(Scheduler):
    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None):
        if udf_specs:
            from ..compiler import activate_udf_specs

            activate_udf_specs(udf_specs)
        return EmbeddedWorkerHandle(sql, job_id, parallelism, restore_epoch, storage_url,
                                    graph_json)


class ProcessScheduler(Scheduler):
    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None):
        return ProcessWorkerHandle(sql, job_id, parallelism, restore_epoch, storage_url,
                                   udf_specs, graph_json)


class NodeWorkerHandle(WorkerHandle):
    """Controller-side proxy for a worker running under a remote node
    daemon (reference NodeScheduler, schedulers/mod.rs:316): commands go
    over the node's HTTP surface; events and liveness are polled."""

    def __init__(self, node_addr: str, sql: str, job_id: str, parallelism: int,
                 restore_epoch, storage_url, udf_specs, graph_json=None):
        from .node import _get, _post

        self._get, self._post = _get, _post
        self.node_addr = node_addr.rstrip("/")
        r = _post(f"{self.node_addr}/start_worker", {
            "sql": sql, "job_id": job_id, "parallelism": parallelism,
            "restore_epoch": restore_epoch, "storage_url": storage_url,
            "udf_specs": udf_specs, "graph_json": graph_json,
        })
        self.worker_id = r["worker_id"]
        self._alive = True
        self._hb = time.monotonic()
        self._buffer: list[dict] = []

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        try:
            self._post(f"{self.node_addr}/workers/{self.worker_id}/send",
                       {"cmd": "checkpoint", "epoch": epoch, "then_stop": then_stop})
        except OSError:
            pass

    def stop(self) -> None:
        try:
            self._post(f"{self.node_addr}/workers/{self.worker_id}/stop", {})
        except OSError:
            pass

    def kill(self) -> None:
        try:
            self._post(f"{self.node_addr}/workers/{self.worker_id}/kill", {})
        except OSError:
            pass
        self._alive = False

    def poll_events(self) -> list[dict]:
        try:
            r = self._get(f"{self.node_addr}/workers/{self.worker_id}/events")
        except OSError:
            # node unreachable: let the heartbeat timeout declare death
            return []
        # anchor to the WORKER's own heartbeat (relayed as an age so clocks
        # need not agree): a hung worker must still trip the controller's
        # heartbeat timeout even though the node daemon answers polls
        self._hb = time.monotonic() - float(r.get("hb_age_s", 0.0))
        self._alive = bool(r["alive"]) or bool(r["events"])
        return r["events"]

    def alive(self) -> bool:
        return self._alive

    def last_heartbeat(self) -> float:
        return self._hb


class LazyNodeWorkerHandle(WorkerHandle):
    """Deferred placement on a node daemon. The controller's supervision
    loop is single-threaded, so start_worker must not block while the
    cluster is briefly full or a daemon is mid-restart: this handle retries
    placement from poll_events (same shape as KubernetesWorkerHandle) and
    queues control commands issued before placement lands."""

    def __init__(self, sched: "NodeScheduler", args: tuple,
                 placement_timeout_s: float):
        self._sched = sched
        self._args = args
        self._deadline = time.monotonic() + placement_timeout_s
        self._inner: Optional[NodeWorkerHandle] = None
        self._queued: list[tuple] = []
        self._dead = False
        self._last = "no live node daemons registered"

    def _try_place(self) -> Optional[list[dict]]:
        inner, self._last = self._sched._place_once(self._args, self._last)
        if inner is not None:
            self._inner = inner
            for cmd in self._queued:
                getattr(inner, cmd[0])(*cmd[1:])
            self._queued.clear()
            return None
        if time.monotonic() > self._deadline:
            self._dead = True
            return [{"event": "failed", "error": f"placement failed: {self._last}"}]
        return None

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        if self._inner is None:
            self._queued.append(("trigger_checkpoint", epoch, then_stop))
        else:
            self._inner.trigger_checkpoint(epoch, then_stop)

    def stop(self) -> None:
        if self._inner is None:
            self._queued.append(("stop",))
        else:
            self._inner.stop()

    def kill(self) -> None:
        self._dead = True
        if self._inner is not None:
            self._inner.kill()

    def poll_events(self) -> list[dict]:
        if self._dead:
            return []
        if self._inner is None:
            return self._try_place() or []
        return self._inner.poll_events()

    def alive(self) -> bool:
        if self._dead:
            return False
        return True if self._inner is None else self._inner.alive()

    def last_heartbeat(self) -> float:
        if self._inner is None:
            return time.monotonic()  # placement has its own deadline
        return self._inner.last_heartbeat()


class NodeScheduler(Scheduler):
    """Places workers on registered node daemons (least-loaded first)."""

    def __init__(self, db):
        self.db = db

    def _place_once(self, args: tuple, last: str):
        """One placement sweep over live daemons -> (handle|None, reason)."""
        import urllib.error

        from .node import _get

        nodes = self.db.list_nodes(alive_within_s=10.0)
        candidates = []
        for n in nodes:
            try:
                st = _get(f"{n['addr']}/status", timeout=5.0)
            except OSError:
                continue
            free = int(st["slots"]) - int(st["used"])
            if free >= 1:
                candidates.append((free, n))
        candidates.sort(key=lambda fn: -fn[0])
        for _free, n in candidates:
            try:
                return NodeWorkerHandle(n["addr"], *args), last
            except urllib.error.HTTPError as e:
                last = f"node {n['id']} rejected placement: {e}"
            except OSError as e:
                last = f"node {n['id']} unreachable: {e}"
        if nodes and not candidates:
            last = "no node daemon with free slots"
        return None, last

    def start_worker(self, sql, job_id, parallelism, restore_epoch, storage_url=None,
                     udf_specs=None, graph_json=None,
                     placement_timeout_s: float = 30.0):
        args = (sql, job_id, parallelism, restore_epoch, storage_url,
                udf_specs, graph_json)
        # fast path: place immediately when capacity exists, so the common
        # case still fails fast on hard errors and tests see a live handle
        handle, last = self._place_once(args, "no live node daemons registered")
        if handle is not None:
            return handle
        lazy = LazyNodeWorkerHandle(self, args, placement_timeout_s)
        lazy._last = last
        return lazy


def scheduler_for(name: str, db=None) -> Scheduler:
    if name == "embedded":
        return EmbeddedScheduler()
    if name == "process":
        return ProcessScheduler()
    if name == "node":
        if db is None:
            raise ValueError("node scheduler needs the shared database")
        return NodeScheduler(db)
    if name == "kubernetes":
        if db is None:
            raise ValueError("kubernetes scheduler needs the shared database")
        from .kube import KubernetesScheduler

        return KubernetesScheduler(db)
    raise ValueError(
        f"unknown scheduler {name!r} (have: embedded, process, node, kubernetes)")
