"""Job state machine.

Mirrors the reference FSM (crates/arroyo-controller/src/states/mod.rs:47-228):
Created -> Compiling -> Scheduling -> Running, with Recovering / Restarting /
Rescaling / Evolving / CheckpointStopping / Stopping and terminal Failed /
Finished / Stopped. Transitions are validated so illegal jumps fail loudly.

Evolving (live pipeline evolution, this repo's addition) mirrors Rescaling:
the running set drains behind a final checkpoint, the controller re-plans
the NEW SQL, writes the evolution mapping the plan-diff pass proved sound
(analysis/plan_diff.py), and the evolved plan re-enters Scheduling restoring
carried state from the drained checkpoint.

The multi-tenant fleet (controller/fleet.py) adds QUEUED between
Compiling and Scheduling: a job the shared pool cannot place (or whose
tenant is at quota) waits there — Pending -> Queued -> Scheduled — and is
admitted by the fleet's deficit-round-robin pass when capacity frees.
Scheduling/Running re-enter Queued when placement is rejected (node 409);
CheckpointStopping/Stopping re-enter it when a quota change preempts the
job (drain behind a checkpoint, then back into the queue).
"""

from __future__ import annotations

import enum


class JobState(enum.Enum):
    CREATED = "Created"
    COMPILING = "Compiling"
    QUEUED = "Queued"
    SCHEDULING = "Scheduling"
    RUNNING = "Running"
    RECOVERING = "Recovering"
    RESTARTING = "Restarting"
    RESCALING = "Rescaling"
    EVOLVING = "Evolving"
    CHECKPOINT_STOPPING = "CheckpointStopping"
    STOPPING = "Stopping"
    FINISHING = "Finishing"
    FAILED = "Failed"
    FINISHED = "Finished"
    STOPPED = "Stopped"


TERMINAL = {JobState.FAILED, JobState.FINISHED, JobState.STOPPED}

# legal transitions (reference states/mod.rs transition table)
TRANSITIONS: dict[JobState, set[JobState]] = {
    JobState.CREATED: {JobState.COMPILING, JobState.FAILED, JobState.STOPPED},
    JobState.COMPILING: {JobState.SCHEDULING, JobState.QUEUED,
                         JobState.FAILED, JobState.STOPPED},
    # Queued -> Stopped is the cancel path: nothing is running, so a stop
    # request takes effect immediately without a drain
    JobState.QUEUED: {JobState.SCHEDULING, JobState.STOPPED, JobState.FAILED},
    JobState.SCHEDULING: {JobState.RUNNING, JobState.FAILED, JobState.STOPPED,
                          JobState.RECOVERING, JobState.QUEUED},
    # Running -> Queued: a deferred (lazy) placement was finally rejected
    # by every node — the job never actually ran and re-queues
    JobState.RUNNING: {JobState.RECOVERING, JobState.RESTARTING, JobState.RESCALING,
                       JobState.EVOLVING,
                       JobState.CHECKPOINT_STOPPING, JobState.STOPPING,
                       JobState.FINISHING, JobState.FINISHED, JobState.FAILED,
                       JobState.QUEUED},
    JobState.RECOVERING: {JobState.SCHEDULING, JobState.QUEUED,
                          JobState.FAILED, JobState.STOPPED},
    JobState.RESTARTING: {JobState.SCHEDULING, JobState.QUEUED,
                          JobState.FAILED, JobState.STOPPED},
    JobState.RESCALING: {JobState.SCHEDULING, JobState.FAILED, JobState.STOPPED},
    # Evolving: v1 drains behind a final checkpoint, then the evolved plan
    # re-enters Scheduling with the carried-state mapping applied at restore
    JobState.EVOLVING: {JobState.SCHEDULING, JobState.FAILED, JobState.STOPPED},
    # *Stopping -> Queued: a quota-change preemption drains the set behind
    # a final checkpoint, then the job re-enters the admission queue
    JobState.CHECKPOINT_STOPPING: {JobState.STOPPING, JobState.STOPPED,
                                   JobState.FAILED, JobState.QUEUED},
    JobState.STOPPING: {JobState.STOPPED, JobState.FAILED, JobState.QUEUED},
    JobState.FINISHING: {JobState.FINISHED, JobState.FAILED},
    JobState.FAILED: {JobState.RESTARTING},  # manual restart of a failed job
    JobState.FINISHED: set(),
    JobState.STOPPED: {JobState.RESTARTING},
}


class IllegalTransition(RuntimeError):
    pass


def check_transition(cur: JobState, nxt: JobState) -> None:
    if nxt not in TRANSITIONS[cur]:
        raise IllegalTransition(f"job cannot go {cur.value} -> {nxt.value}")
