"""Job state machine.

Mirrors the reference FSM (crates/arroyo-controller/src/states/mod.rs:47-228):
Created -> Compiling -> Scheduling -> Running, with Recovering / Restarting /
Rescaling / CheckpointStopping / Stopping and terminal Failed / Finished /
Stopped. Transitions are validated so illegal jumps fail loudly.
"""

from __future__ import annotations

import enum


class JobState(enum.Enum):
    CREATED = "Created"
    COMPILING = "Compiling"
    SCHEDULING = "Scheduling"
    RUNNING = "Running"
    RECOVERING = "Recovering"
    RESTARTING = "Restarting"
    RESCALING = "Rescaling"
    CHECKPOINT_STOPPING = "CheckpointStopping"
    STOPPING = "Stopping"
    FINISHING = "Finishing"
    FAILED = "Failed"
    FINISHED = "Finished"
    STOPPED = "Stopped"


TERMINAL = {JobState.FAILED, JobState.FINISHED, JobState.STOPPED}

# legal transitions (reference states/mod.rs transition table)
TRANSITIONS: dict[JobState, set[JobState]] = {
    JobState.CREATED: {JobState.COMPILING, JobState.FAILED, JobState.STOPPED},
    JobState.COMPILING: {JobState.SCHEDULING, JobState.FAILED, JobState.STOPPED},
    JobState.SCHEDULING: {JobState.RUNNING, JobState.FAILED, JobState.STOPPED,
                          JobState.RECOVERING},
    JobState.RUNNING: {JobState.RECOVERING, JobState.RESTARTING, JobState.RESCALING,
                       JobState.CHECKPOINT_STOPPING, JobState.STOPPING,
                       JobState.FINISHING, JobState.FINISHED, JobState.FAILED},
    JobState.RECOVERING: {JobState.SCHEDULING, JobState.FAILED, JobState.STOPPED},
    JobState.RESTARTING: {JobState.SCHEDULING, JobState.FAILED, JobState.STOPPED},
    JobState.RESCALING: {JobState.SCHEDULING, JobState.FAILED, JobState.STOPPED},
    JobState.CHECKPOINT_STOPPING: {JobState.STOPPING, JobState.STOPPED, JobState.FAILED},
    JobState.STOPPING: {JobState.STOPPED, JobState.FAILED},
    JobState.FINISHING: {JobState.FINISHED, JobState.FAILED},
    JobState.FAILED: {JobState.RESTARTING},  # manual restart of a failed job
    JobState.FINISHED: set(),
    JobState.STOPPED: {JobState.RESTARTING},
}


class IllegalTransition(RuntimeError):
    pass


def check_transition(cur: JobState, nxt: JobState) -> None:
    if nxt not in TRANSITIONS[cur]:
        raise IllegalTransition(f"job cannot go {cur.value} -> {nxt.value}")
