"""Node daemon: per-machine worker launcher.

Equivalent of crates/arroyo-node (lib.rs:47 NodeServer, :65
start_worker_int): an agent that runs on every machine of a cluster,
registers itself (address + task slots) with the control plane, and
launches/kills worker processes on demand. The reference speaks gRPC in
both directions; here the node exposes a small JSON-over-HTTP surface and
registers/heartbeats through the REST API, and the controller's
NodeScheduler (scheduler.py) places workers on registered nodes and polls
their event streams — same topology, HTTP instead of tonic.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..obs.lockorder import make_lock


def _post(url: str, body: dict, timeout: float = 10.0) -> dict:
    from ..config import config

    headers = {"Content-Type": "application/json"}
    token = config().get("api.auth-token")
    if token:
        # the cluster API gates mutating requests when a token is set
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST", headers=headers,
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class NodeServer:
    """The per-machine agent. start() registers with the controller API and
    begins heartbeating; workers are spawned as subprocesses via the same
    ProcessWorkerHandle the process scheduler uses, with their event
    streams buffered for the controller to poll."""

    def __init__(self, api_base: str, slots: int = 16,
                 host: str = "127.0.0.1", port: int = 0,
                 advertise_host: Optional[str] = None):
        from ..config import config

        self.api_base = api_base.rstrip("/")
        self.slots = slots
        # explicit id (config node.id / ARROYO_TPU__NODE__ID) lets the
        # kubernetes scheduler correlate the pod it created with the node
        # registration that dials home
        self.node_id = config().get("node.id") or f"node_{uuid.uuid4().hex[:12]}"
        self._workers: dict[str, object] = {}  # worker_id -> ProcessWorkerHandle
        self._lock = make_lock("NodeServer._lock")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, payload) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else {}

            def do_POST(self):
                outer._route(self, "POST")

            def do_GET(self):
                outer._route(self, "GET")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self.httpd.server_address[1]
        # the address the CONTROLLER dials; binding 0.0.0.0 still needs a
        # routable name advertised to the cluster
        self.addr = f"http://{advertise_host or host}:{self.port}"
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # --------------------------------------------------------------- routes

    _ROUTES = [
        ("POST", r"^/start_worker$", "_start_worker"),
        ("POST", r"^/workers/([^/]+)/stop$", "_stop_worker"),
        ("POST", r"^/workers/([^/]+)/kill$", "_kill_worker"),
        ("POST", r"^/workers/([^/]+)/send$", "_send_worker"),
        ("GET", r"^/workers/([^/]+)/events$", "_worker_events"),
        ("GET", r"^/status$", "_status"),
    ]

    # ThreadingHTTPServer runs each request on its own thread; everything
    # _route reaches shares that role (the static auditor cannot see
    # through BaseHTTPRequestHandler dispatch)
    # thread: http-request
    def _route(self, h, method: str) -> None:
        path = h.path.split("?", 1)[0]
        for m, pat, name in self._ROUTES:
            if m != method:
                continue
            match = re.match(pat, path)
            if match:
                try:
                    getattr(self, name)(h, *match.groups())
                except Exception as e:  # noqa: BLE001
                    h._json(500, {"error": str(e)})
                return
        h._json(404, {"error": f"no route {method} {path}"})

    def _start_worker(self, h) -> None:
        from ..faults import fault_point
        from .scheduler import ProcessWorkerHandle

        body = h._body()
        # chaos hook: a failed admission surfaces as HTTP 500 and exercises
        # the scheduler's placement retry/fallback path
        fault_point("node.start_worker", job=str(body.get("job_id", "")))
        wid = f"worker_{uuid.uuid4().hex[:12]}"
        with self._lock:
            # a None value is another request's under-lock reservation whose
            # handle is still being spawned — it holds a slot too
            used = sum(1 for w in self._workers.values()
                       if w is None or w.alive())
            if used >= self.slots:
                # slots are a hard admission limit, not advisory — the
                # scheduler's status poll races concurrent placements
                h._json(409, {"error": f"node full ({used}/{self.slots} slots)"})
                return
            self._workers[wid] = None  # reserve under the lock
        try:
            handle = ProcessWorkerHandle(
                body["sql"], body["job_id"], int(body.get("parallelism", 1)),
                body.get("restore_epoch"), body.get("storage_url"),
                body.get("udf_specs"), body.get("graph_json"),
                # multi-worker set placement: this worker's slice of the
                # assignment plus its data-plane bind (peers dial in)
                worker_index=body.get("worker_index"),
                n_workers=int(body.get("n_workers") or 1),
                assignment=body.get("assignment"),
                dp_bind=body.get("dp_bind"),
            )
        except BaseException:
            # spawn failure must release the reservation or the slot is
            # consumed forever (fatal on 1-slot kubernetes worker pods)
            with self._lock:
                self._workers.pop(wid, None)
            raise
        with self._lock:
            self._workers[wid] = handle
        h._json(200, {"worker_id": wid})

    def _handle(self, wid: str):
        with self._lock:
            return self._workers.get(wid)  # None while still being spawned

    def _stop_worker(self, h, wid) -> None:
        handle = self._handle(wid)
        if handle is None:
            h._json(404, {"error": "unknown worker"})
            return
        handle.stop()
        h._json(200, {})

    def _kill_worker(self, h, wid) -> None:
        handle = self._handle(wid)
        if handle is None:
            h._json(404, {"error": "unknown worker"})
            return
        handle.kill()
        with self._lock:
            self._workers.pop(wid, None)
        h._json(200, {})

    def _send_worker(self, h, wid) -> None:
        """Forward a control command (checkpoint/stop) to the worker's
        stdin protocol."""
        handle = self._handle(wid)
        if handle is None:
            h._json(404, {"error": "unknown worker"})
            return
        handle._send(h._body())
        h._json(200, {})

    def _worker_events(self, h, wid) -> None:
        handle = self._handle(wid)
        if handle is None:
            h._json(404, {"error": "unknown worker"})
            return
        events = handle.poll_events()
        alive = handle.alive()
        h._json(200, {
            "events": events,
            "alive": alive,
            # real worker liveness, not node-daemon reachability: the
            # controller's hang detection needs the worker's own heartbeat
            "hb_age_s": time.monotonic() - handle.last_heartbeat(),
        })
        if not alive and not events:
            # exited and fully drained: reap (kill() on a dead process only
            # releases pipes and the temp sql/udf files)
            handle.kill()
            with self._lock:
                self._workers.pop(wid, None)

    def _status(self, h) -> None:
        with self._lock:
            used = sum(1 for w in self._workers.values()
                       if w is None or w.alive())
        h._json(200, {"node_id": self.node_id, "slots": self.slots, "used": used})

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "NodeServer":
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True,
                             name=f"arroyo-node-{self.port}")
        t.start()
        self._threads.append(t)
        self._register()
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        hb.start()
        self._threads.append(hb)
        return self

    def _register(self) -> None:
        _post(f"{self.api_base}/api/v1/nodes/register", {
            "node_id": self.node_id, "addr": self.addr, "slots": self.slots,
        })

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(2.0):
            try:
                _post(f"{self.api_base}/api/v1/nodes/{self.node_id}/heartbeat", {})
            except Exception:
                pass  # controller restart: re-register on next beat
                try:
                    self._register()
                except Exception:  # lint: waive LR102 — controller restart window: the next heartbeat re-registers; nothing to do here
                    pass

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        with self._lock:
            for w in self._workers.values():
                if w is None:
                    continue  # in-flight reservation, nothing to kill yet
                try:
                    w.kill()
                except Exception:  # lint: waive LR102 — best-effort kill at daemon shutdown; worker may already have exited
                    pass
            self._workers.clear()
