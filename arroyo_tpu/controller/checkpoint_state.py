"""Controller-owned cross-worker checkpoint coordination.

Equivalent of crates/arroyo-controller/src/job_controller/checkpoint_state.rs:
the CONTROL PLANE — not any one worker — collects per-subtask
``checkpoint_completed`` acks from every worker of a job, declares the epoch
globally durable by writing the job-level metadata marker only once EVERY
expected subtask has reported (or finished), and only then fans phase-2
``commit`` messages back out to the workers (send_commit_messages,
job_controller/mod.rs:838). Workers running under an assignment never write
job metadata and never self-commit (engine/engine.py relays acks upward
instead), so a committing sink can never finalize against an epoch that
another worker has yet to make durable.

The 2PC ordering invariant — metadata durable across all workers BEFORE any
commit message leaves the controller — is recorded in ``event_log`` as an
ordered trail (("metadata_durable", epoch) strictly precedes every
("commit_sent", epoch, worker)), which the chaos suite asserts directly.

Commit delivery is at-least-once and cumulative: ``Engine.deliver_commit(E)``
first delivers any earlier durable epoch whose commit message was lost (the
``commit`` chaos site drops them on purpose), so a dropped phase-2 message is
re-delivered with the next epoch, never lost.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence

from ..obs.trace import recorder as trace_recorder
from ..state.tables import write_job_checkpoint_metadata

SubtaskKey = tuple[str, int]  # (node_id, subtask_index)


def expected_subtasks(graph) -> set[SubtaskKey]:
    """Every (node_id, subtask) of a job = the global ack set for an epoch.
    Must be computed against the SAME post-chaining graph the workers run
    (compute_assignment chains first for exactly this reason)."""
    return {
        (nid, s)
        for nid, node in graph.nodes.items()
        for s in range(node.parallelism)
    }


def compute_assignment(graph_json: str, n_workers: int):
    """Place every subtask on a worker (reference compute_assignments,
    states/scheduling.rs:56): round-robin over each node's subtasks so every
    worker holds a slice of every operator — sources included, which keeps
    barrier injection local to each worker.

    Returns ``(assignment, expected, n_actual)``; ``n_actual`` is clamped to
    the widest node so no worker is left with zero subtasks.
    """
    from ..config import config
    from ..graph import Graph

    g = Graph.loads(graph_json)
    if config().get("pipeline.chaining.enabled"):
        # the engine chains its own copy deterministically; assignments must
        # be keyed by the post-chaining node ids or Engine.__init__ rejects
        from ..optimizer import chain_graph

        g = chain_graph(g)
    widest = max((n.parallelism for n in g.nodes.values()), default=1)
    n_actual = max(1, min(int(n_workers), widest))
    assignment = {
        (nid, s): s % n_actual
        for nid, node in g.nodes.items()
        for s in range(node.parallelism)
    }
    return assignment, expected_subtasks(g), n_actual


@dataclass
class CheckpointState:
    """One epoch's cross-worker progress (reference CheckpointState)."""

    epoch: int
    started_at: float
    acked: set = field(default_factory=set)
    publishing: bool = False  # metadata write claimed (single-writer guard)
    # per-epoch integrity manifest, accumulated from the envelopes each
    # subtask ack relays ({"operator-<node>/<file>": {crc,len,algo}}) and
    # folded into the job-level marker at publish time
    integrity: dict = field(default_factory=dict)

    def covered_by(self, finished: set, expected: frozenset) -> bool:
        """Global coverage: every expected subtask either acked this epoch
        or finished outright (a drained task's state is final — reference
        CheckpointState handles TaskFinished the same way)."""
        return expected <= (self.acked | finished)


class CheckpointCoordinator:
    """Tracks every in-flight epoch for one multi-worker job and owns the
    two-phase commit: phase 1 completes when the job-level metadata marker
    is durable (global coverage), phase 2 fans commits to the workers."""

    def __init__(self, job_id: str, storage_url: str,
                 expected: Iterable[SubtaskKey],
                 event_log: Optional[list] = None,
                 plan_hash: Optional[str] = None):
        self.job_id = job_id
        self.storage_url = storage_url
        self.expected = frozenset(expected)
        # plan fingerprint stamped into every epoch's job-level metadata so
        # a later restore can prove it reads state its plan actually wrote
        self.plan_hash = plan_hash
        self._lock = threading.Lock()
        self.pending: dict[int, CheckpointState] = {}
        self.finished: set[SubtaskKey] = set()
        self.durable: list[int] = []  # epochs in durability order
        self.forgotten: set[int] = set()  # subsumed stuck epochs: drop late acks
        # ordered 2PC trail (("metadata_durable", e) / ("commit_sent", e, w) /
        # ("commit_dropped", e, w) / ("subtask_acked", e, node, sub)); shared
        # with the JobController so it survives worker-set restarts
        self.event_log: list[tuple] = event_log if event_log is not None else []

    # ------------------------------------------------------------- phase 1

    def begin(self, epoch: int) -> None:
        with self._lock:
            if epoch not in self.forgotten and epoch not in self.durable:
                self.pending.setdefault(
                    epoch, CheckpointState(epoch, time.monotonic()))

    def on_ack(self, epoch: int, key: SubtaskKey,
               integrity: Optional[dict] = None) -> Optional[int]:
        """Record one subtask's checkpoint-completed ack (``integrity`` is
        its artifact-envelope contribution to the epoch manifest). Returns
        the epoch if this ack made it globally durable (metadata marker
        written)."""
        with self._lock:
            if epoch in self.forgotten or epoch in self.durable:
                return None  # late ack for a subsumed or already-durable epoch
            st = self.pending.setdefault(
                epoch, CheckpointState(epoch, time.monotonic()))
            st.acked.add(key)
            if integrity:
                st.integrity.update(integrity)
            self.event_log.append(("subtask_acked", epoch, key[0], key[1]))
            if st.publishing or not st.covered_by(self.finished, self.expected):
                return None
            st.publishing = True
        self._publish(st)
        return epoch

    def on_task_finished(self, key: SubtaskKey) -> list[int]:
        """A subtask drained; it can no longer take part in barriers, so any
        pending epoch may just have reached coverage. Returns the epochs
        that became durable."""
        with self._lock:
            self.finished.add(key)
            ready = []
            for st in sorted(self.pending.values(), key=lambda s: s.epoch):
                if not st.publishing and st.covered_by(self.finished, self.expected):
                    st.publishing = True
                    ready.append(st)
        for st in ready:
            self._publish(st)
        return [st.epoch for st in ready]

    def _publish(self, st: CheckpointState) -> None:
        """Write the job-level metadata marker — the durability commit point
        of phase 1. Runs outside the lock (storage can block); ``publishing``
        guarantees a single writer per epoch."""
        with self._lock:
            operators = sorted({k[0] for k in st.acked}
                               | {k[0] for k in (self.finished & self.expected)})
        extra = {"operators": operators}
        if self.plan_hash:
            extra["plan_hash"] = self.plan_hash
        if st.integrity:
            extra["integrity"] = dict(sorted(st.integrity.items()))
        write_job_checkpoint_metadata(
            self.storage_url, self.job_id, st.epoch, extra)
        trace_recorder.record(self.job_id, st.epoch, "metadata_durable")
        with self._lock:
            self.pending.pop(st.epoch, None)
            self.durable.append(st.epoch)
            self.event_log.append(("metadata_durable", st.epoch))

    # ------------------------------------------------------------- phase 2

    def send_commits(self, epoch: int,
                     senders: Sequence[Optional[Callable[[int], None]]]) -> None:
        """Fan the phase-2 commit out to every worker (reference
        send_commit_messages). Only ever called for durable epochs — the
        event log proves the ordering. The ``commit`` chaos site drops
        messages here; recovery is the cumulative re-delivery in
        Engine.deliver_commit, not a retry loop."""
        from ..faults import fault_point

        for widx, send in enumerate(senders):
            if send is None:
                continue  # worker already finished and was reaped
            verdict = fault_point("commit", epoch=epoch, worker=widx)
            if verdict is not None and verdict[0] == "drop":
                with self._lock:
                    self.event_log.append(("commit_dropped", epoch, widx))
                continue
            send(epoch)
            trace_recorder.record(self.job_id, epoch, "commit_sent",
                                  worker=widx)
            with self._lock:
                self.event_log.append(("commit_sent", epoch, widx))

    # ------------------------------------------------------------ recovery

    def outstanding(self, epoch: int) -> list[SubtaskKey]:
        """Subtasks that never acked ``epoch`` (stuck-checkpoint diagnostic)."""
        with self._lock:
            st = self.pending.get(epoch)
            if st is None:
                return []
            return sorted(self.expected - st.acked - self.finished)

    def forget(self, epoch: int) -> None:
        """Abandon a wedged epoch (its torn shards are being subsumed);
        late acks for it are dropped instead of resurrecting it."""
        with self._lock:
            self.pending.pop(epoch, None)
            self.forgotten.add(epoch)


class EngineSetCoordinator:
    """Controller-style coordination for a set of in-process Engines sharing
    one job (multi-worker test harnesses and embedded worker sets driven
    without a full ControllerServer): pumps each engine's coordinator event
    queue into a CheckpointCoordinator and fans phase-2 commits back via
    Engine.deliver_commit."""

    def __init__(self, engines: Sequence, storage_url: Optional[str] = None):
        e0 = engines[0]
        self.engines = list(engines)
        self.coordinator = CheckpointCoordinator(
            e0.job_id, storage_url or e0.storage_url, expected_subtasks(e0.graph))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name=f"ckpt-coord-{e0.job_id}")

    def start(self) -> "EngineSetCoordinator":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    @property
    def event_log(self) -> list[tuple]:
        return self.coordinator.event_log

    def _pump(self) -> None:
        while not self._stop.is_set():
            moved = False
            for eng in self.engines:
                while True:
                    try:
                        ev = eng.coordinator_events.get_nowait()
                    except _queue.Empty:
                        break
                    moved = True
                    self._handle(ev)
            if not moved:
                self._stop.wait(0.02)

    def _handle(self, ev: dict) -> None:
        if ev.get("event") == "subtask_acked":
            durable = self.coordinator.on_ack(
                int(ev["epoch"]), (ev["node"], int(ev["subtask"])),
                integrity=ev.get("integrity"))
            if durable is not None:
                self._commit(durable)
        elif ev.get("event") == "subtask_finished":
            for epoch in self.coordinator.on_task_finished(
                    (ev["node"], int(ev["subtask"]))):
                self._commit(epoch)

    def _commit(self, epoch: int) -> None:
        self.coordinator.send_commits(
            epoch, [e.deliver_commit for e in self.engines])
