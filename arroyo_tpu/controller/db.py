"""Pipeline/job store.

Equivalent of the reference's Postgres/SQLite DB shared by arroyo-api and
arroyo-controller (cornucopia queries; controller polls it for desired-state
changes, lib.rs:543-567). SQLite via the stdlib; one writer lock because the
API server and controller share a process in the embedded deployment.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import uuid
from typing import Any, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS pipelines (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL,
    query TEXT NOT NULL,
    parallelism INTEGER NOT NULL DEFAULT 1,
    version INTEGER NOT NULL DEFAULT 1,  -- bumped by each live evolution
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS jobs (
    id TEXT PRIMARY KEY,
    pipeline_id TEXT NOT NULL REFERENCES pipelines(id),
    state TEXT NOT NULL,
    desired_stop TEXT,            -- NULL | 'checkpoint' | 'immediate'
    desired_parallelism INTEGER,  -- non-NULL requests a live rescale
    desired_query TEXT,           -- non-NULL requests a live evolution
    restarts INTEGER NOT NULL DEFAULT 0,
    n_workers INTEGER NOT NULL DEFAULT 1,  -- size of the running worker set
    checkpoint_epoch INTEGER NOT NULL DEFAULT 0,
    restore_epoch INTEGER,
    failure_message TEXT,
    run_id INTEGER NOT NULL DEFAULT 0,
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS nodes (
    id TEXT PRIMARY KEY,
    addr TEXT NOT NULL,
    slots INTEGER NOT NULL,
    last_heartbeat REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS udfs (
    name TEXT PRIMARY KEY,
    language TEXT NOT NULL,       -- 'cpp' | 'python'
    source TEXT NOT NULL,
    arg_dtypes TEXT NOT NULL,     -- JSON list (cpp only)
    return_dtype TEXT NOT NULL,
    artifact_url TEXT,            -- built dylib (cpp only)
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS connection_profiles (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL UNIQUE,
    connector TEXT NOT NULL,
    config TEXT NOT NULL,         -- JSON options shared by tables
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS connection_tables (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL UNIQUE,
    connector TEXT NOT NULL,
    profile_id TEXT REFERENCES connection_profiles(id),
    table_type TEXT NOT NULL,     -- 'source' | 'sink'
    config TEXT NOT NULL,         -- JSON connector options
    schema_fields TEXT NOT NULL,  -- JSON [{name, type, nullable}]
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS checkpoints (
    job_id TEXT NOT NULL,
    epoch INTEGER NOT NULL,
    state TEXT NOT NULL,          -- 'inprogress' | 'complete' | 'compacted' | 'failed'
    time REAL NOT NULL,
    phases TEXT,                  -- JSON {align,snapshot,ack,commit: seconds}
    PRIMARY KEY (job_id, epoch)
);
CREATE TABLE IF NOT EXISTS job_traces (
    job_id TEXT NOT NULL,
    epoch INTEGER NOT NULL,
    events TEXT NOT NULL,         -- JSON epoch-lifecycle span events
    updated_at REAL NOT NULL,
    PRIMARY KEY (job_id, epoch)
);
CREATE TABLE IF NOT EXISTS job_outputs (
    job_id TEXT NOT NULL,
    seq INTEGER NOT NULL,
    line TEXT NOT NULL,
    PRIMARY KEY (job_id, seq)
);
CREATE TABLE IF NOT EXISTS job_metrics (
    job_id TEXT PRIMARY KEY,
    data TEXT NOT NULL,
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS job_profiles (
    job_id TEXT PRIMARY KEY,
    data TEXT NOT NULL,           -- JSON compact per-operator cost profile
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS job_events (
    job_id TEXT NOT NULL,
    seq INTEGER NOT NULL,         -- controller-side event-log seq (cursor)
    ts_us INTEGER NOT NULL,
    level TEXT NOT NULL,          -- DEBUG | INFO | WARN | ERROR
    code TEXT NOT NULL,           -- stable EventCode (obs.events)
    node TEXT,                    -- scope: operator node id
    subtask INTEGER,
    worker INTEGER,
    epoch INTEGER,
    message TEXT NOT NULL,
    data TEXT,                    -- JSON extra payload
    PRIMARY KEY (job_id, seq)
);
CREATE TABLE IF NOT EXISTS job_health (
    job_id TEXT PRIMARY KEY,
    state TEXT NOT NULL,          -- ok | degraded | critical
    data TEXT NOT NULL,           -- JSON per-rule detail (obs.health)
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS fleet_state (
    id INTEGER PRIMARY KEY CHECK (id = 1),  -- singleton snapshot row
    data TEXT NOT NULL,           -- JSON (controller/fleet.py stats())
    updated_at REAL NOT NULL
);
"""

_OUTPUT_CAP = 10_000  # preview rows retained per job


class Database:
    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        with self._lock:
            self._conn.executescript(_SCHEMA)
            # additive migration for databases created by older builds
            # (CREATE TABLE IF NOT EXISTS leaves existing tables untouched)
            for migration in (
                "ALTER TABLE jobs ADD COLUMN desired_parallelism INTEGER",
                "ALTER TABLE jobs ADD COLUMN n_workers INTEGER NOT NULL DEFAULT 1",
                "ALTER TABLE jobs ADD COLUMN health TEXT",
                "ALTER TABLE jobs ADD COLUMN tenant TEXT NOT NULL DEFAULT 'default'",
                "ALTER TABLE jobs ADD COLUMN desired_query TEXT",
                "ALTER TABLE pipelines ADD COLUMN version INTEGER NOT NULL DEFAULT 1",
                "ALTER TABLE checkpoints ADD COLUMN phases TEXT",
            ):
                try:
                    self._conn.execute(migration)
                except sqlite3.OperationalError as e:
                    if "duplicate column" not in str(e).lower():
                        raise  # locked/readonly/corrupt db: fail loudly, not later
            self._conn.commit()

    # ------------------------------------------------------------ pipelines

    def create_pipeline(self, name: str, query: str, parallelism: int = 1) -> str:
        pid = f"pl_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO pipelines (id, name, query, parallelism, created_at) "
                "VALUES (?,?,?,?,?)",
                (pid, name, query, parallelism, time.time()),
            )
            self._conn.commit()
        return pid

    def get_pipeline(self, pid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute("SELECT * FROM pipelines WHERE id=?", (pid,)).fetchone()
        return dict(row) if row else None

    def list_pipelines(self) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM pipelines ORDER BY created_at DESC"
            ).fetchall()
        return [dict(r) for r in rows]

    def set_pipeline_parallelism(self, pid: str, parallelism: int) -> None:
        """Persist a completed rescale so restarts keep the new scale."""
        with self._lock:
            self._conn.execute(
                "UPDATE pipelines SET parallelism=? WHERE id=?", (parallelism, pid))
            self._conn.commit()

    def evolve_pipeline_query(self, pid: str, query: str) -> int:
        """Persist a completed live evolution: the pipeline's query becomes
        the evolved SQL and its version lineage advances. Returns the new
        version. Restarts re-plan from this row, so a job restarted after
        the evolution committed runs the evolved plan."""
        with self._lock:
            self._conn.execute(
                "UPDATE pipelines SET query=?, version=version+1 WHERE id=?",
                (query, pid))
            self._conn.commit()
            row = self._conn.execute(
                "SELECT version FROM pipelines WHERE id=?", (pid,)).fetchone()
        return int(row["version"]) if row else 0

    def clear_desired_query(self, jid: str, expected: str) -> None:
        """Clear the evolve request iff it still holds the SQL we just
        applied; a newer concurrent request survives to trigger again."""
        with self._lock:
            self._conn.execute(
                "UPDATE jobs SET desired_query=NULL, updated_at=? "
                "WHERE id=? AND desired_query=?",
                (time.time(), jid, expected))
            self._conn.commit()

    def delete_pipeline(self, pid: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM jobs WHERE pipeline_id=?", (pid,))
            self._conn.execute("DELETE FROM pipelines WHERE id=?", (pid,))
            self._conn.commit()

    # ----------------------------------------------------------------- jobs

    def create_job(self, pipeline_id: str, tenant: str = "default") -> str:
        """``tenant`` keys the fleet's per-tenant admission queues and
        quotas (controller/fleet.py)."""
        jid = f"job_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO jobs (id, pipeline_id, state, tenant, "
                "updated_at) VALUES (?,?,?,?,?)",
                (jid, pipeline_id, "Created", tenant or "default",
                 time.time()),
            )
            self._conn.commit()
        return jid

    def get_job(self, jid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute("SELECT * FROM jobs WHERE id=?", (jid,)).fetchone()
        return dict(row) if row else None

    def list_jobs(self, pipeline_id: Optional[str] = None) -> list[dict]:
        with self._lock:
            if pipeline_id:
                rows = self._conn.execute(
                    "SELECT * FROM jobs WHERE pipeline_id=? ORDER BY updated_at DESC",
                    (pipeline_id,),
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT * FROM jobs ORDER BY updated_at DESC"
                ).fetchall()
        return [dict(r) for r in rows]

    def set_desired_parallelism_if_unset(self, jid: str, target: int) -> bool:
        """Compare-and-set for the autoscaler's actuation: the write lands
        only while no rescale request is pending, so a manual PATCH racing
        in between the controller's job-row read and this write is never
        clobbered (manual requests always win). Returns True iff set."""
        with self._lock:
            cur = self._conn.execute(
                "UPDATE jobs SET desired_parallelism=?, updated_at=? "
                "WHERE id=? AND desired_parallelism IS NULL",
                (int(target), time.time(), jid))
            self._conn.commit()
            return cur.rowcount > 0

    def clear_desired_parallelism(self, jid: str, expected: int) -> None:
        """Clear the rescale request iff it still holds the value we just
        applied; a newer concurrent request survives to trigger again."""
        with self._lock:
            self._conn.execute(
                "UPDATE jobs SET desired_parallelism=NULL, updated_at=? "
                "WHERE id=? AND desired_parallelism=?",
                (time.time(), jid, expected))
            self._conn.commit()

    def update_job(self, jid: str, **fields: Any) -> None:
        if not fields:
            return
        cols = ", ".join(f"{k}=?" for k in fields)
        with self._lock:
            self._conn.execute(
                f"UPDATE jobs SET {cols}, updated_at=? WHERE id=?",
                (*fields.values(), time.time(), jid),
            )
            self._conn.commit()

    # ----------------------------------------------------------------- nodes

    def register_node(self, node_id: str, addr: str, slots: int) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO nodes (id, addr, slots, last_heartbeat) VALUES (?,?,?,?) "
                "ON CONFLICT(id) DO UPDATE SET addr=excluded.addr, "
                "slots=excluded.slots, last_heartbeat=excluded.last_heartbeat",
                (node_id, addr, slots, time.time()),
            )
            self._conn.commit()

    def node_heartbeat(self, node_id: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE nodes SET last_heartbeat=? WHERE id=?", (time.time(), node_id)
            )
            self._conn.commit()
            return cur.rowcount > 0

    def list_nodes(self, alive_within_s: Optional[float] = None) -> list[dict]:
        with self._lock:
            rows = self._conn.execute("SELECT * FROM nodes ORDER BY id").fetchall()
        out = [dict(r) for r in rows]
        if alive_within_s is not None:
            cutoff = time.time() - alive_within_s
            out = [n for n in out if n["last_heartbeat"] >= cutoff]
        return out

    # ------------------------------------------------------------------ udfs

    def create_udf(self, name: str, language: str, source: str,
                   arg_dtypes: list[str], return_dtype: str,
                   artifact_url: Optional[str]) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO udfs (name, language, source, arg_dtypes, "
                "return_dtype, artifact_url, created_at) VALUES (?,?,?,?,?,?,?) "
                "ON CONFLICT(name) DO UPDATE SET language=excluded.language, "
                "source=excluded.source, arg_dtypes=excluded.arg_dtypes, "
                "return_dtype=excluded.return_dtype, "
                "artifact_url=excluded.artifact_url",
                (name, language, source, json.dumps(arg_dtypes), return_dtype,
                 artifact_url, time.time()),
            )
            self._conn.commit()

    def list_udfs(self) -> list[dict]:
        with self._lock:
            rows = self._conn.execute("SELECT * FROM udfs ORDER BY name").fetchall()
        out = []
        for r in rows:
            d = dict(r)
            d["arg_dtypes"] = json.loads(d["arg_dtypes"])
            out.append(d)
        return out

    def delete_udf(self, name: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM udfs WHERE name=?", (name,))
            self._conn.commit()

    # ------------------------------------------------- connection tables

    def create_connection_profile(self, name: str, connector: str,
                                  config: dict) -> str:
        cid = f"cp_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO connection_profiles (id, name, connector, config, "
                "created_at) VALUES (?,?,?,?,?)",
                (cid, name, connector, json.dumps(config), time.time()))
            self._conn.commit()
        return cid

    def list_connection_profiles(self) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM connection_profiles ORDER BY name").fetchall()
        out = []
        for r in rows:
            d = dict(r)
            d["config"] = json.loads(d["config"])
            out.append(d)
        return out

    def delete_connection_profile(self, cid: str) -> bool:
        with self._lock:
            used = self._conn.execute(
                "SELECT COUNT(*) FROM connection_tables WHERE profile_id=?",
                (cid,)).fetchone()[0]
            if used:
                return False
            self._conn.execute(
                "DELETE FROM connection_profiles WHERE id=?", (cid,))
            self._conn.commit()
        return True

    def create_connection_table(self, name: str, connector: str,
                                table_type: str, config: dict,
                                schema_fields: list[dict],
                                profile_id: Optional[str] = None) -> str:
        tid = f"ct_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO connection_tables (id, name, connector, profile_id, "
                "table_type, config, schema_fields, created_at) "
                "VALUES (?,?,?,?,?,?,?,?)",
                (tid, name, connector, profile_id, table_type,
                 json.dumps(config), json.dumps(schema_fields), time.time()))
            self._conn.commit()
        return tid

    def list_connection_tables(self) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM connection_tables ORDER BY name").fetchall()
        out = []
        for r in rows:
            d = dict(r)
            d["config"] = json.loads(d["config"])
            d["schema_fields"] = json.loads(d["schema_fields"])
            out.append(d)
        return out

    def delete_connection_table(self, tid: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM connection_tables WHERE id=?", (tid,))
            self._conn.commit()

    # ---------------------------------------------------------- checkpoints

    def record_checkpoint(self, job_id: str, epoch: int, state: str,
                          phases: Optional[dict] = None) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO checkpoints (job_id, epoch, state, time, phases) "
                "VALUES (?,?,?,?,?) "
                "ON CONFLICT(job_id, epoch) DO UPDATE SET state=excluded.state, "
                "time=excluded.time, "
                # a later state-only update ('compacted') keeps the phases
                "phases=COALESCE(excluded.phases, checkpoints.phases)",
                (job_id, epoch, state, time.time(),
                 json.dumps(phases) if phases else None),
            )
            self._conn.commit()

    def list_checkpoints(self, job_id: str) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM checkpoints WHERE job_id=? ORDER BY epoch", (job_id,)
            ).fetchall()
        return [dict(r) for r in rows]

    _TRACE_CAP = 32  # newest epochs retained per job (mirrors the recorder)

    def record_trace(self, job_id: str, epoch: int, events: list[dict]) -> None:
        """Persist one epoch's lifecycle span events (obs.trace), bounded to
        the newest _TRACE_CAP epochs per job."""
        if not events:
            return
        with self._lock:
            self._conn.execute(
                "INSERT INTO job_traces (job_id, epoch, events, updated_at) "
                "VALUES (?,?,?,?) ON CONFLICT(job_id, epoch) DO UPDATE SET "
                "events=excluded.events, updated_at=excluded.updated_at",
                (job_id, epoch, json.dumps(events), time.time()),
            )
            self._conn.execute(
                "DELETE FROM job_traces WHERE job_id=? AND epoch NOT IN ("
                "SELECT epoch FROM job_traces WHERE job_id=? "
                "ORDER BY epoch DESC LIMIT ?)",
                (job_id, job_id, self._TRACE_CAP),
            )
            self._conn.commit()

    def list_traces(self, job_id: str,
                    epoch: Optional[int] = None) -> list[dict]:
        """[{epoch, events: [...]}] oldest epoch first."""
        with self._lock:
            if epoch is None:
                rows = self._conn.execute(
                    "SELECT epoch, events FROM job_traces WHERE job_id=? "
                    "ORDER BY epoch", (job_id,)).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT epoch, events FROM job_traces WHERE job_id=? "
                    "AND epoch=?", (job_id, epoch)).fetchall()
        return [{"epoch": int(r["epoch"]), "events": json.loads(r["events"])}
                for r in rows]

    # -------------------------------------------------- preview output

    def record_output(self, job_id: str, lines: list[str]) -> None:
        """Append preview sink rows (reference: SendSinkData gRPC rows
        buffered controller-side for the UI), bounded per job."""
        if not lines:
            return
        with self._lock:
            row = self._conn.execute(
                "SELECT COALESCE(MAX(seq), -1) AS m FROM job_outputs WHERE job_id=?",
                (job_id,),
            ).fetchone()
            seq = int(row["m"]) + 1
            self._conn.executemany(
                "INSERT INTO job_outputs (job_id, seq, line) VALUES (?,?,?)",
                [(job_id, seq + i, l) for i, l in enumerate(lines)],
            )
            self._conn.execute(
                "DELETE FROM job_outputs WHERE job_id=? AND seq <= ?",
                (job_id, seq + len(lines) - 1 - _OUTPUT_CAP),
            )
            self._conn.commit()

    def list_outputs(self, job_id: str, after_seq: int = -1, limit: int = 1000) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT seq, line FROM job_outputs WHERE job_id=? AND seq > ? "
                "ORDER BY seq LIMIT ?",
                (job_id, after_seq, limit),
            ).fetchall()
        return [dict(r) for r in rows]

    def record_metrics(self, job_id: str, data: dict) -> None:
        """Latest per-operator metrics snapshot (workers ship these over
        the control protocol; reference JobMetrics gRPC + 1s scrape)."""
        with self._lock:
            self._conn.execute(
                "INSERT INTO job_metrics (job_id, data, updated_at) VALUES (?,?,?) "
                "ON CONFLICT(job_id) DO UPDATE SET data=excluded.data, "
                "updated_at=excluded.updated_at",
                (job_id, json.dumps(data), time.time()),
            )
            self._conn.commit()

    def get_metrics(self, job_id: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM job_metrics WHERE job_id=?", (job_id,)
            ).fetchone()
        return json.loads(row["data"]) if row else None

    _EVENTS_CAP = 1000  # newest structured events retained per job

    def record_events(self, job_id: str, events: list[dict]) -> None:
        """Append structured job events (obs.events dicts carrying the
        controller-side ``seq``), bounded to the newest _EVENTS_CAP per
        job. Idempotent per (job, seq): a re-flushed event is skipped
        rather than duplicated."""
        if not events:
            return
        with self._lock:
            self._conn.executemany(
                "INSERT INTO job_events (job_id, seq, ts_us, level, code, "
                "node, subtask, worker, epoch, message, data) "
                "VALUES (?,?,?,?,?,?,?,?,?,?,?) "
                "ON CONFLICT(job_id, seq) DO NOTHING",
                [(job_id, int(e["seq"]), int(e["ts_us"]), e["level"],
                  e["code"], e.get("node"), e.get("subtask"),
                  e.get("worker"), e.get("epoch"), e.get("message", ""),
                  json.dumps(e.get("data") or {}))
                 for e in events],
            )
            self._conn.execute(
                "DELETE FROM job_events WHERE job_id=? AND seq <= ("
                "SELECT MAX(seq) FROM job_events WHERE job_id=?) - ?",
                (job_id, job_id, self._EVENTS_CAP),
            )
            self._conn.commit()

    def list_events(self, job_id: str, level: Optional[str] = None,
                    since: Optional[float] = None, after_seq: int = 0,
                    limit: int = 1000) -> list[dict]:
        """Structured events oldest first; ``level`` is a minimum (WARN
        returns WARN+ERROR), ``since`` a unix-seconds floor, ``after_seq``
        the incremental-tail cursor (`logs --follow` / API ?after=)."""
        from ..obs.events import level_rank

        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM job_events WHERE job_id=? AND seq > ? "
                "ORDER BY seq LIMIT ?",
                (job_id, int(after_seq), int(limit))).fetchall()
        out = []
        floor = level_rank(level) if level is not None else None
        for r in rows:
            e = dict(r)
            e.pop("job_id", None)
            e["data"] = json.loads(e["data"]) if e["data"] else {}
            if floor is not None and level_rank(e["level"]) < floor:
                continue
            if since is not None and e["ts_us"] < since * 1e6:
                continue
            out.append(e)
        return out

    def last_event_seq(self, job_id: str) -> int:
        """Max persisted event seq for a job — a restarted controller
        seeds the in-memory event log past it (obs.events
        ``ensure_seq_floor``) so post-restart events don't collide with
        already-persisted (job, seq) rows."""
        with self._lock:
            row = self._conn.execute(
                "SELECT MAX(seq) AS s FROM job_events WHERE job_id=?",
                (job_id,)).fetchone()
        return int(row["s"] or 0)

    def record_health(self, job_id: str, state: str, data: dict) -> None:
        """Latest per-rule health detail (obs.health.HealthMonitor
        evaluation) behind GET /api/v1/jobs/<id>/health."""
        with self._lock:
            self._conn.execute(
                "INSERT INTO job_health (job_id, state, data, updated_at) "
                "VALUES (?,?,?,?) ON CONFLICT(job_id) DO UPDATE SET "
                "state=excluded.state, data=excluded.data, "
                "updated_at=excluded.updated_at",
                (job_id, state, json.dumps(data), time.time()),
            )
            self._conn.commit()

    def get_health(self, job_id: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT state, data, updated_at FROM job_health WHERE job_id=?",
                (job_id,)).fetchone()
        if row is None:
            return None
        out = json.loads(row["data"])
        out["state"] = row["state"]
        out["updated_at"] = row["updated_at"]
        return out

    def record_fleet_state(self, data: dict) -> None:
        """Latest fleet snapshot (controller/fleet.py stats(): pool size,
        used/free slots, per-tenant usage, the admission queue with
        positions) — what GET /api/v1/fleet and queued jobs' API queue
        positions serve, cross-process."""
        with self._lock:
            self._conn.execute(
                "INSERT INTO fleet_state (id, data, updated_at) "
                "VALUES (1,?,?) ON CONFLICT(id) DO UPDATE SET "
                "data=excluded.data, updated_at=excluded.updated_at",
                (json.dumps(data), time.time()),
            )
            self._conn.commit()

    def get_fleet_state(self) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data, updated_at FROM fleet_state WHERE id=1"
            ).fetchone()
        if row is None:
            return None
        out = json.loads(row["data"])
        out["updated_at"] = row["updated_at"]
        return out

    def fleet_queue_position(self, job_id: str) -> Optional[int]:
        """1-based admission-queue position of a Queued job, from the
        persisted fleet snapshot — the one lookup both the jobs API and
        `top --db` attach to queued job rows."""
        fleet = self.get_fleet_state() or {}
        for e in fleet.get("queue") or []:
            if e.get("job_id") == job_id:
                return e.get("position")
        return None

    def record_profile(self, job_id: str, data: dict) -> None:
        """Latest compact per-operator cost profile (obs.profile.job_profile
        over the merged worker snapshots): busy%, self-time, state sizes,
        hot keys — what `explain`/`/profile` serve."""
        with self._lock:
            self._conn.execute(
                "INSERT INTO job_profiles (job_id, data, updated_at) VALUES (?,?,?) "
                "ON CONFLICT(job_id) DO UPDATE SET data=excluded.data, "
                "updated_at=excluded.updated_at",
                (job_id, json.dumps(data), time.time()),
            )
            self._conn.commit()

    def get_profile(self, job_id: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM job_profiles WHERE job_id=?", (job_id,)
            ).fetchone()
        return json.loads(row["data"]) if row else None

    def close(self) -> None:
        with self._lock:
            self._conn.close()
