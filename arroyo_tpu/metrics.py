"""Task metrics: counters + gauges with prometheus text exposition.

Reference: crates/arroyo-metrics/src/lib.rs — TaskCounters (:91:
arroyo_worker_{messages,batches,bytes}_{recv,sent}, deserialization errors)
and TX-queue gauges (:161-163); scraped via the admin server's /metrics and
aggregated controller-side into rates + backpressure
(job_controller/job_metrics.rs:63-130, backpressure = 1 - rem/size :95).
No prometheus client dependency — the text format is trivial.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import defaultdict
from typing import Optional

_COUNTER_NAMES = (
    "arroyo_worker_messages_recv",
    "arroyo_worker_messages_sent",
    "arroyo_worker_batches_recv",
    "arroyo_worker_batches_sent",
    "arroyo_worker_bytes_recv",
    "arroyo_worker_bytes_sent",
    "arroyo_worker_deserialization_errors",
)


class Histogram:
    """Fixed-bucket histogram (single writer, like the counters)."""

    __slots__ = ("buckets", "counts", "count", "sum")

    def __init__(self, buckets: tuple):
        self.buckets = buckets  # ascending upper bounds; +Inf is implicit
        self.counts = [0] * (len(buckets) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v) -> None:
        self.counts[bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def _quantile(self, q: float) -> tuple[float, bool]:
        """(estimate, overflow): overflow=True means the quantile landed in
        the +Inf bucket and the estimate is clamped to the largest finite
        bound (a lower bound on the true value)."""
        if not self.count:
            return 0.0, False
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                if i < len(self.buckets):
                    return float(self.buckets[i]), False
                break
        return float(self.buckets[-1]), True

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the q-quantile (coalesce
        breakdown lines; not exported — prometheus consumers use _bucket).
        Overflow-bucket hits clamp to the largest finite bound instead of
        returning inf, so downstream arithmetic (bench breakdown lines,
        `top` columns) stays finite/parseable; use quantile_str to surface
        the clamp."""
        return self._quantile(q)[0]

    def quantile_str(self, q: float, scale: float = 1.0,
                     precision: int = 2) -> str:
        """quantile(q) * scale formatted for breakdown lines; a clamped
        overflow estimate is flagged with a leading '>' (it is only a
        lower bound)."""
        v, overflow = self._quantile(q)
        s = f"{v * scale:.{precision}f}"
        return f">{s}" if overflow else s


# emitted batch sizes in rows (powers of two to the queue-budget scale)
EMIT_ROWS_BUCKETS = tuple(1 << i for i in range(17))  # 1 .. 65536
# queue-transit wall latency in seconds (100us .. 2.5s)
TRANSIT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
# sink-side end-to-end event latency (wall clock at the sink minus the
# event's _timestamp): real deployments sit in the ms..minutes range;
# synthetic generators with epoch-0 timestamps land in the overflow bucket,
# which quantile() clamps (flagged '>' by quantile_str)
SINK_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0, 3600.0)
# checkpoint phase durations (align/snapshot/ack/commit), seconds
PHASE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)
_HISTOGRAM_NAMES = ("arroyo_worker_emit_batch_rows",
                    "arroyo_worker_queue_transit_seconds",
                    "arroyo_worker_sink_event_latency_seconds")
CHECKPOINT_PHASES = ("align", "snapshot", "ack", "commit")
# self-time categories the task run loop attributes operator work to
# (ISSUE 7): watermark handling (window closes) counts as "process" —
# it is data-path work driven by the stream, not bookkeeping
SELF_TIME_CATEGORIES = ("process", "tick", "close", "checkpoint")


class TaskMetrics:
    """Per-subtask counters (lock-free: single writer per task thread)."""

    __slots__ = ("job_id", "node_id", "subtask", "counters", "queue_size",
                 "queue_rem", "emit_batch_rows", "queue_transit",
                 "sink_event_latency", "watermark_micros", "self_time",
                 "self_cpu", "late_rows", "state_rows", "state_bytes",
                 "sketch", "started_monotonic", "segment_compiled",
                 "segment_reason", "spill", "segment_mesh", "mesh")

    def __init__(self, job_id: str, node_id: str, subtask: int):
        self.job_id = job_id
        self.node_id = node_id
        self.subtask = subtask
        self.counters = dict.fromkeys(_COUNTER_NAMES, 0)
        self.queue_size = 0
        self.queue_rem = 0
        # coalescing instrumentation: per-operator emitted-batch-size and
        # inbox transit-latency distributions (ISSUE 5 — the win is
        # measured, not asserted)
        self.emit_batch_rows = Histogram(EMIT_ROWS_BUCKETS)
        self.queue_transit = Histogram(TRANSIT_BUCKETS)
        # event-time health (ISSUE 6): the task run loop stamps the current
        # merged watermark here; lag (= processing time minus watermark,
        # reference arroyo-metrics) is derived at export time. Sinks observe
        # per-batch end-to-end event latency.
        self.sink_event_latency = Histogram(SINK_LATENCY_BUCKETS)
        self.watermark_micros: Optional[int] = None
        # cost attribution (ISSUE 7), written only by the owning task
        # thread: wall + thread-CPU self-time seconds per category, the
        # late/expired-row counter, live state-size gauges per table, and
        # the key-skew sketch (obs.sketch.KeySketch, attached by the task
        # when profiling is enabled). busy%, cost-per-row, and hot-key
        # shares are derived at export time — never in the hot path.
        self.self_time = dict.fromkeys(SELF_TIME_CATEGORIES, 0.0)
        self.self_cpu = dict.fromkeys(SELF_TIME_CATEGORIES, 0.0)
        self.late_rows = 0
        self.state_rows: dict[str, int] = {}
        self.state_bytes: dict[str, int] = {}
        self.sketch = None
        self.started_monotonic = time.monotonic()
        # whole-segment compilation (engine/segment.py): True once this
        # subtask's chained segment runs as one jitted call, False after a
        # fallback, None for operators the compiler never considered —
        # `top` and `explain` render the [compiled] marker from this
        self.segment_compiled: Optional[bool] = None
        # why the segment is NOT compiled: the plan-time reject reason
        # (optimizer.chain_graph "not compilable: ...") or the runtime
        # fallback reason (SEGMENT_FALLBACK) — `top` and `explain` render
        # it next to the [compiled] marker
        self.segment_reason: Optional[str] = None
        # tiered state (state/spill.py): {"bytes_total", "hot", "cold",
        # "probe_files": Histogram}, set by TaskProfiler.refresh from the
        # operator's spill_stats() hook; None while nothing ever spilled
        self.spill: Optional[dict] = None
        # fused mesh execution (engine/segment.py mesh path): True once
        # this subtask committed a micro-batch through the ONE shard_map'd
        # program — `top`/`explain` render the [mesh] marker from this
        self.segment_mesh: Optional[bool] = None
        # sharded-aggregate residency: {"exchange_rows", "overflow_rows"},
        # set by TaskProfiler.refresh from the operator's mesh_stats()
        # hook; None off the mesh path -> arroyo_mesh_* series
        self.mesh: Optional[dict] = None

    def histogram(self, name: str) -> Histogram:
        # explicit mapping: an unknown/typoed name must fail loudly at the
        # first export, not silently serve another series' counts
        return {
            "arroyo_worker_emit_batch_rows": self.emit_batch_rows,
            "arroyo_worker_queue_transit_seconds": self.queue_transit,
            "arroyo_worker_sink_event_latency_seconds": self.sink_event_latency,
        }[name]

    def add(self, name: str, v: int = 1) -> None:
        self.counters[name] += v

    def backpressure(self) -> float:
        """1 - queue_remaining/queue_size (reference job_metrics.rs:95)."""
        if self.queue_size <= 0:
            return 0.0
        return max(0.0, 1.0 - self.queue_rem / self.queue_size)

    def watermark_lag_seconds(self, now_us: Optional[float] = None) -> Optional[float]:
        """Processing time minus current event-time watermark (seconds);
        None until a watermark reached this subtask."""
        if self.watermark_micros is None:
            return None
        now_us = time.time() * 1e6 if now_us is None else now_us
        return max(0.0, (now_us - self.watermark_micros) / 1e6)

    def uptime_seconds(self) -> float:
        return max(1e-9, time.monotonic() - self.started_monotonic)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: dict[tuple[str, str, int], TaskMetrics] = {}
        # (job_id, phase) -> Histogram of per-epoch phase durations; fed by
        # whoever declares an epoch durable (engine single-worker, the
        # controller's coordinator otherwise) from the epoch trace
        self._phases: dict[tuple[str, str], Histogram] = {}
        # job_id -> ok|degraded|critical, set by the controller's health
        # monitors each supervision tick (obs/health.py)
        self._job_health: dict[str, str] = {}
        # job_id -> target parallelism, set by the controller's elastic
        # autoscaler (controller/autoscaler.py) when enabled: the in-flight
        # target while a scale actuates, else the current parallelism
        self._autoscaler_target: dict[str, int] = {}
        # whole-segment compilation (engine/segment.py): per-job histogram
        # of trace+XLA-compile wall seconds (one observation per compiled
        # (segment, schema, padded-shape)), and the compile-cache hit count
        self._segment_compile: dict[str, Histogram] = {}
        self._segment_cache_hits: dict[str, int] = {}
        # multi-tenant fleet snapshot (controller/fleet.py stats()), set
        # once per ControllerServer tick; None until a fleet pass ran
        self._fleet: Optional[dict] = None
        # (job_id, operator) -> records dropped under bad_data=drop; fed by
        # the shared deserializer policy (formats/base.py) so every
        # connector counts drops identically
        self._bad_records: dict[tuple[str, str], int] = {}

    def set_job_health(self, job_id: str, state: str) -> None:
        with self._lock:
            self._job_health[job_id] = state

    def set_autoscaler_target(self, job_id: str, target: int) -> None:
        with self._lock:
            self._autoscaler_target[job_id] = int(target)

    def set_fleet_stats(self, stats: Optional[dict]) -> None:
        with self._lock:
            self._fleet = stats

    def add_bad_record(self, job_id: str, operator: str, n: int = 1) -> None:
        key = (job_id, operator)
        with self._lock:
            self._bad_records[key] = self._bad_records.get(key, 0) + int(n)

    def bad_records(self, job_id: str) -> dict[str, int]:
        """operator -> dropped-record count for one job (API/test probe)."""
        with self._lock:
            return {op: n for (j, op), n in self._bad_records.items()
                    if j == job_id}

    def task(self, job_id: str, node_id: str, subtask: int) -> TaskMetrics:
        key = (job_id, node_id, subtask)
        with self._lock:
            tm = self._tasks.get(key)
            if tm is None:
                tm = TaskMetrics(job_id, node_id, subtask)
                self._tasks[key] = tm
            return tm

    def observe_segment_compile(self, job_id: str, seconds: float) -> None:
        with self._lock:
            h = self._segment_compile.get(job_id)
            if h is None:
                h = self._segment_compile[job_id] = Histogram(PHASE_BUCKETS)
            h.observe(float(seconds))

    def add_segment_cache_hit(self, job_id: str) -> None:
        with self._lock:
            self._segment_cache_hits[job_id] = \
                self._segment_cache_hits.get(job_id, 0) + 1

    def segment_compile_stats(self, job_id: str) -> tuple[int, int]:
        """(compiles observed, cache hits) for one job — test/CLI probe."""
        with self._lock:
            h = self._segment_compile.get(job_id)
            return (h.count if h else 0,
                    self._segment_cache_hits.get(job_id, 0))

    def observe_epoch_phases(self, job_id: str, phases: dict) -> None:
        """Record one completed epoch's phase durations (seconds)."""
        with self._lock:
            for phase, secs in phases.items():
                if phase not in CHECKPOINT_PHASES:
                    continue
                h = self._phases.get((job_id, phase))
                if h is None:
                    h = self._phases[(job_id, phase)] = Histogram(PHASE_BUCKETS)
                h.observe(float(secs))

    def phase_histograms(self, job_id: str) -> dict[str, Histogram]:
        with self._lock:
            return {p: h for (j, p), h in self._phases.items() if j == job_id}

    def snapshot(self) -> list[TaskMetrics]:
        with self._lock:
            return list(self._tasks.values())

    def clear_job(self, job_id: str) -> None:
        with self._lock:
            self._tasks = {
                k: v for k, v in self._tasks.items() if k[0] != job_id
            }
            self._phases = {
                k: v for k, v in self._phases.items() if k[0] != job_id
            }
            self._job_health.pop(job_id, None)
            self._autoscaler_target.pop(job_id, None)
            self._segment_compile.pop(job_id, None)
            self._segment_cache_hits.pop(job_id, None)
            self._bad_records = {
                k: v for k, v in self._bad_records.items() if k[0] != job_id
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format (served at /metrics)."""
        lines: list[str] = []
        tasks = self.snapshot()
        for name in _COUNTER_NAMES:
            lines.append(f"# TYPE {name} counter")
            for t in tasks:
                lines.append(
                    f'{name}{{job="{t.job_id}",operator="{t.node_id}",'
                    f'subtask="{t.subtask}"}} {t.counters[name]}'
                )
        lines.append("# TYPE arroyo_worker_tx_queue_size gauge")
        lines.append("# TYPE arroyo_worker_tx_queue_rem gauge")
        for t in tasks:
            label = (f'job="{t.job_id}",operator="{t.node_id}",'
                     f'subtask="{t.subtask}"')
            lines.append(f"arroyo_worker_tx_queue_size{{{label}}} {t.queue_size}")
            lines.append(f"arroyo_worker_tx_queue_rem{{{label}}} {t.queue_rem}")
        lines.append("# TYPE arroyo_worker_watermark_lag_seconds gauge")
        now_us = time.time() * 1e6
        for t in tasks:
            lag = t.watermark_lag_seconds(now_us)
            if lag is None:
                continue
            label = (f'job="{t.job_id}",operator="{t.node_id}",'
                     f'subtask="{t.subtask}"')
            lines.append(
                f"arroyo_worker_watermark_lag_seconds{{{label}}} {lag:.6f}")

        # cost attribution (ISSUE 7): per-category self-time counters, the
        # late/expired-row counter, and live state-size gauges per table
        lines.append("# TYPE arroyo_worker_self_time_seconds counter")
        lines.append("# TYPE arroyo_worker_self_cpu_seconds counter")
        for t in tasks:
            for cat in SELF_TIME_CATEGORIES:
                if not t.self_time[cat] and not t.self_cpu[cat]:
                    continue
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}",category="{cat}"')
                lines.append(
                    f"arroyo_worker_self_time_seconds{{{label}}} "
                    f"{t.self_time[cat]:.6f}")
                lines.append(
                    f"arroyo_worker_self_cpu_seconds{{{label}}} "
                    f"{t.self_cpu[cat]:.6f}")
        lines.append("# TYPE arroyo_late_rows_total counter")
        for t in tasks:
            if not t.late_rows:
                continue
            lines.append(
                f'arroyo_late_rows_total{{job="{t.job_id}",'
                f'operator="{t.node_id}",subtask="{t.subtask}"}} '
                f"{t.late_rows}")
        lines.append("# TYPE arroyo_state_rows gauge")
        lines.append("# TYPE arroyo_state_bytes gauge")
        for t in tasks:
            for table in sorted(t.state_rows):
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}",table="{table}"')
                lines.append(
                    f"arroyo_state_rows{{{label}}} {t.state_rows[table]}")
                lines.append(
                    f"arroyo_state_bytes{{{label}}} "
                    f"{t.state_bytes.get(table, 0)}")

        # tiered state (state/spill.py): cumulative spilled bytes, the
        # hot/cold partition split, and the files-touched-per-probe
        # histogram (the bloom/zone-map pruning-effectiveness signal)
        spill_tasks = [t for t in tasks if t.spill]
        if spill_tasks:
            lines.append("# TYPE arroyo_spill_bytes_total counter")
            lines.append("# TYPE arroyo_spill_partitions gauge")
            for t in spill_tasks:
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}"')
                lines.append(
                    f"arroyo_spill_bytes_total{{{label}}} "
                    f"{t.spill['bytes_total']}")
                lines.append(
                    f'arroyo_spill_partitions{{{label},state="hot"}} '
                    f"{t.spill['hot']}")
                lines.append(
                    f'arroyo_spill_partitions{{{label},state="cold"}} '
                    f"{t.spill['cold']}")

        # fused mesh execution (parallel/sharded_agg.py): rows fed through
        # the in-program keyed exchange, and the current per-shard HBM
        # spill-buffer residency (key skew past a fixed exchange lane)
        mesh_tasks = [t for t in tasks if t.mesh]
        if mesh_tasks:
            lines.append("# TYPE arroyo_mesh_exchange_rows_total counter")
            lines.append("# TYPE arroyo_mesh_overflow_rows gauge")
            for t in mesh_tasks:
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}"')
                lines.append(
                    f"arroyo_mesh_exchange_rows_total{{{label}}} "
                    f"{t.mesh.get('exchange_rows', 0)}")
                lines.append(
                    f"arroyo_mesh_overflow_rows{{{label}}} "
                    f"{t.mesh.get('overflow_rows', 0)}")

        def emit_histogram(name: str, label: str, h: Histogram) -> None:
            cum = 0
            for le, c in zip(h.buckets, h.counts):
                cum += c
                lines.append(f'{name}_bucket{{{label},le="{le}"}} {cum}')
            lines.append(f'{name}_bucket{{{label},le="+Inf"}} {h.count}')
            lines.append(f"{name}_sum{{{label}}} {h.sum}")
            lines.append(f"{name}_count{{{label}}} {h.count}")

        for name in _HISTOGRAM_NAMES:
            lines.append(f"# TYPE {name} histogram")
            for t in tasks:
                h = t.histogram(name)
                if not h.count:
                    continue
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}"')
                emit_histogram(name, label, h)
        if spill_tasks:
            lines.append("# TYPE arroyo_spill_probe_files histogram")
            for t in spill_tasks:
                h = t.spill.get("probe_files")
                if h is None or not h.count:
                    continue
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}"')
                emit_histogram("arroyo_spill_probe_files", label, h)
        with self._lock:
            phase_hists = sorted(self._phases.items())
            job_health = sorted(self._job_health.items())
            autoscaler_targets = sorted(self._autoscaler_target.items())
            segment_compiles = sorted(self._segment_compile.items())
            segment_hits = sorted(self._segment_cache_hits.items())
        # whole-segment compilation (engine/segment.py): compile-time
        # distribution + compile-cache hits per job
        if segment_compiles:
            lines.append("# TYPE arroyo_segment_compile_seconds histogram")
            for job, h in segment_compiles:
                emit_histogram("arroyo_segment_compile_seconds",
                               f'job="{job}"', h)
        if segment_hits:
            lines.append("# TYPE arroyo_segment_cache_hits_total counter")
            for job, n in segment_hits:
                lines.append(
                    f'arroyo_segment_cache_hits_total{{job="{job}"}} {n}')
        if phase_hists:
            lines.append("# TYPE arroyo_checkpoint_phase_seconds histogram")
            for (job, phase), h in phase_hists:
                emit_histogram("arroyo_checkpoint_phase_seconds",
                               f'job="{job}",phase="{phase}"', h)
        # health state per job (0 ok / 1 degraded / 2 critical) and the
        # structured-event counters (obs/events.py rings keep the newest
        # events; these counts keep the totals)
        if job_health:
            from .obs.health import health_value

            lines.append("# TYPE arroyo_job_health gauge")
            for job, state in job_health:
                lines.append(
                    f'arroyo_job_health{{job="{job}",state="{state}"}} '
                    f"{health_value(state)}")
        if autoscaler_targets:
            lines.append("# TYPE arroyo_autoscaler_target gauge")
            for job, target in autoscaler_targets:
                lines.append(
                    f'arroyo_autoscaler_target{{job="{job}"}} {target}')
        # multi-tenant fleet: slot occupancy, per-tenant admission-queue
        # depth, and the fleet autoscaler's pool target. Slot/target
        # series only export for a BOUNDED pool (an unlimited pass-through
        # fleet has no meaningful occupancy number); queue depth exports
        # whenever jobs are queued.
        with self._lock:
            fleet = self._fleet
        if fleet is not None:
            if fleet.get("pool_slots") is not None:
                lines.append("# TYPE arroyo_fleet_slots gauge")
                lines.append(
                    f'arroyo_fleet_slots{{state="used"}} '
                    f"{int(fleet.get('slots_used') or 0)}")
                lines.append(
                    f'arroyo_fleet_slots{{state="free"}} '
                    f"{int(fleet.get('slots_free') or 0)}")
                lines.append("# TYPE arroyo_fleet_target_workers gauge")
                lines.append(
                    f"arroyo_fleet_target_workers "
                    f"{int(fleet.get('target_workers') or 0)}")
            depth = fleet.get("queue_depth") or {}
            if depth:
                lines.append("# TYPE arroyo_fleet_queue_depth gauge")
                for tenant, n in sorted(depth.items()):
                    # tenant is the one FREE-TEXT (user-supplied) label in
                    # this exposition: escape per the text format or a
                    # quote/newline in a tenant name corrupts the whole
                    # scrape
                    esc = (str(tenant).replace("\\", "\\\\")
                           .replace('"', '\\"').replace("\n", "\\n"))
                    lines.append(
                        f'arroyo_fleet_queue_depth{{tenant="{esc}"}} {n}')
        with self._lock:
            bad = sorted(self._bad_records.items())
        if bad:
            lines.append("# TYPE arroyo_bad_records_total counter")
            for (job, op), n in bad:
                lines.append(
                    f'arroyo_bad_records_total{{job="{job}",'
                    f'operator="{op}"}} {n}')
        from .obs.events import recorder as _events_recorder

        counts = _events_recorder.counts_snapshot()
        if counts:
            lines.append("# TYPE arroyo_events_total counter")
            for (job, code, level), n in sorted(counts.items()):
                lines.append(
                    f'arroyo_events_total{{job="{job}",code="{code}",'
                    f'level="{level}"}} {n}')
        return "\n".join(lines) + "\n"

    def job_metrics(self, job_id: str) -> dict:
        """Per-operator aggregates for the API
        (reference /operator_metric_groups). Carries a ``per_subtask``
        breakdown so the controller can merge snapshots from a multi-worker
        set without double-counting (each worker reports its own subtasks;
        union by subtask label is exact)."""
        from .config import config as _config

        topk = int(_config().get("profile.sketch.topk", 5) or 5)
        now_us = time.time() * 1e6
        out: dict[str, dict] = {}
        for t in self.snapshot():
            if t.job_id != job_id:
                continue
            op = out.setdefault(t.node_id, {"per_subtask": {}})
            lag = t.watermark_lag_seconds(now_us)
            transit_p99 = (round(t.queue_transit.quantile(0.99) * 1000, 3)
                           if t.queue_transit.count else None)
            sink_p99 = (round(t.sink_event_latency.quantile(0.99), 3)
                        if t.sink_event_latency.count else None)
            entry = {
                **{name: t.counters[name] for name in _COUNTER_NAMES},
                "backpressure": round(t.backpressure(), 4),
                "watermark_lag_seconds": lag if lag is None else round(lag, 3),
                "queue_transit_p99_ms": transit_p99,
                "sink_event_latency_p99_s": sink_p99,
                # cost attribution (ISSUE 7): busy% and cost-per-row are
                # derived HERE, at export — never in the hot path
                "uptime_seconds": round(t.uptime_seconds(), 3),
                "busy_pct": round(
                    100.0 * sum(t.self_time.values()) / t.uptime_seconds(), 2),
                "self_time": {c: round(v, 6) for c, v in t.self_time.items()},
                "self_cpu": {c: round(v, 6) for c, v in t.self_cpu.items()},
                "late_rows": t.late_rows,
                "state_rows": dict(t.state_rows),
                "state_bytes": dict(t.state_bytes),
            }
            if t.segment_compiled is not None:
                entry["segment_compiled"] = t.segment_compiled
            if t.segment_reason is not None:
                entry["segment_reason"] = t.segment_reason
            if t.segment_mesh is not None:
                entry["segment_mesh"] = t.segment_mesh
            if t.mesh is not None:
                entry["mesh"] = dict(t.mesh)
            if t.sketch is not None and t.sketch.total:
                # fixed-width hex: merges deterministically (merge_topk) and
                # survives JSON without 64-bit precision loss
                entry["hot_keys"] = [
                    {**e, "key": f"{e['key']:016x}"}
                    for e in t.sketch.topk(topk)]
                entry["sketch_total"] = t.sketch.total
            op["per_subtask"][str(t.subtask)] = entry
        return {op: _op_aggregate(m["per_subtask"]) for op, m in out.items()}


def _op_aggregate(per_subtask: dict[str, dict]) -> dict:
    """Fold a per-subtask breakdown into one operator row (counters summed,
    health gauges maxed — the worst subtask is the one an operator cares
    about). Rate fields default to 0 and are overwritten by the
    controller's windowed tracker while the job runs, so the field contract
    holds for every consumer (UI charts, `top`)."""
    # profile fields (self-time sums, worst-subtask busy%, state gauges,
    # merged hot keys) fold through one shared helper so a multi-worker
    # union aggregates exactly like a local snapshot
    from .obs.profile import aggregate_profiles

    def _max_opt(key):
        vals = [s[key] for s in per_subtask.values() if s.get(key) is not None]
        return max(vals) if vals else None

    out = {
        "subtasks": len(per_subtask),
        **{name: sum(int(s.get(name, 0)) for s in per_subtask.values())
           for name in _COUNTER_NAMES},
        "backpressure": max((float(s.get("backpressure", 0.0))
                             for s in per_subtask.values()), default=0.0),
        "messages_per_sec": 0.0,
        "messages_recv_per_sec": 0.0,
        "watermark_lag_seconds": _max_opt("watermark_lag_seconds"),
        "queue_transit_p99_ms": _max_opt("queue_transit_p99_ms"),
        "sink_event_latency_p99_s": _max_opt("sink_event_latency_p99_s"),
        "per_subtask": per_subtask,
        **aggregate_profiles(per_subtask),
    }
    if any(s.get("segment_compiled") for s in per_subtask.values()):
        out["segment_compiled"] = True
    if any(s.get("segment_mesh") for s in per_subtask.values()):
        out["segment_mesh"] = True
    mesh = [s["mesh"] for s in per_subtask.values() if s.get("mesh")]
    if mesh:
        out["mesh"] = {k: sum(int(m.get(k, 0)) for m in mesh)
                       for k in ("exchange_rows", "overflow_rows")}
    reasons = sorted({s["segment_reason"] for s in per_subtask.values()
                      if s.get("segment_reason")})
    if reasons:
        out["segment_reason"] = reasons[0]
    process_s = (out.get("self_time") or {}).get("process")
    recv = out.get("arroyo_worker_messages_recv", 0)
    if process_s and recv:
        out["self_us_per_row"] = round(process_s * 1e6 / recv, 3)
    return out


def merge_job_metrics(snapshots) -> dict:
    """Union per-operator snapshots shipped by the workers of one job into
    a single controller-side view. Subtask labels are globally unique under
    an assignment (each worker owns a disjoint slice), so union-by-label is
    exact; embedded worker sets sharing one process registry report
    identical full snapshots, which the union collapses instead of
    double-counting."""
    per_op: dict[str, dict[str, dict]] = {}
    for snap in snapshots:
        for op, m in (snap or {}).items():
            if not isinstance(m, dict):
                continue
            per = m.get("per_subtask")
            if not per:
                # legacy flat snapshot (no breakdown): synthesize one entry
                per = {"*": {name: m.get(name, 0) for name in _COUNTER_NAMES}}
            per_op.setdefault(op, {}).update(per)
    return {op: _op_aggregate(per) for op, per in per_op.items()}


registry = MetricsRegistry()


class RateTracker:
    """Windowed rate computation (reference job_metrics.rs rate windows)."""

    def __init__(self, window_s: float = 10.0):
        self.window_s = window_s
        self._points: dict[str, list[tuple[float, int]]] = defaultdict(list)

    def observe(self, key: str, value: int, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        pts = self._points[key]
        pts.append((now, value))
        cutoff = now - self.window_s
        while len(pts) > 2 and pts[0][0] < cutoff:
            pts.pop(0)

    def reset(self) -> None:
        """Drop all points — counters are about to restart from zero (e.g.
        a replacement worker set), so old points would yield negative rates."""
        self._points.clear()

    def rate(self, key: str) -> float:
        pts = self._points.get(key)
        if not pts or len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return (v1 - v0) / (t1 - t0)
