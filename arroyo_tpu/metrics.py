"""Task metrics: counters + gauges with prometheus text exposition.

Reference: crates/arroyo-metrics/src/lib.rs — TaskCounters (:91:
arroyo_worker_{messages,batches,bytes}_{recv,sent}, deserialization errors)
and TX-queue gauges (:161-163); scraped via the admin server's /metrics and
aggregated controller-side into rates + backpressure
(job_controller/job_metrics.rs:63-130, backpressure = 1 - rem/size :95).
No prometheus client dependency — the text format is trivial.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import defaultdict
from typing import Optional

_COUNTER_NAMES = (
    "arroyo_worker_messages_recv",
    "arroyo_worker_messages_sent",
    "arroyo_worker_batches_recv",
    "arroyo_worker_batches_sent",
    "arroyo_worker_bytes_recv",
    "arroyo_worker_bytes_sent",
    "arroyo_worker_deserialization_errors",
)


class Histogram:
    """Fixed-bucket histogram (single writer, like the counters)."""

    __slots__ = ("buckets", "counts", "count", "sum")

    def __init__(self, buckets: tuple):
        self.buckets = buckets  # ascending upper bounds; +Inf is implicit
        self.counts = [0] * (len(buckets) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v) -> None:
        self.counts[bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the q-quantile (coalesce
        breakdown lines; not exported — prometheus consumers use _bucket)."""
        if not self.count:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return float(self.buckets[i]) if i < len(self.buckets) \
                    else float("inf")
        return float("inf")


# emitted batch sizes in rows (powers of two to the queue-budget scale)
EMIT_ROWS_BUCKETS = tuple(1 << i for i in range(17))  # 1 .. 65536
# queue-transit wall latency in seconds (100us .. 2.5s)
TRANSIT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
_HISTOGRAM_NAMES = ("arroyo_worker_emit_batch_rows",
                    "arroyo_worker_queue_transit_seconds")


class TaskMetrics:
    """Per-subtask counters (lock-free: single writer per task thread)."""

    __slots__ = ("job_id", "node_id", "subtask", "counters", "queue_size",
                 "queue_rem", "emit_batch_rows", "queue_transit")

    def __init__(self, job_id: str, node_id: str, subtask: int):
        self.job_id = job_id
        self.node_id = node_id
        self.subtask = subtask
        self.counters = dict.fromkeys(_COUNTER_NAMES, 0)
        self.queue_size = 0
        self.queue_rem = 0
        # coalescing instrumentation: per-operator emitted-batch-size and
        # inbox transit-latency distributions (ISSUE 5 — the win is
        # measured, not asserted)
        self.emit_batch_rows = Histogram(EMIT_ROWS_BUCKETS)
        self.queue_transit = Histogram(TRANSIT_BUCKETS)

    def histogram(self, name: str) -> Histogram:
        # explicit mapping: an unknown/typoed name must fail loudly at the
        # first export, not silently serve another series' counts
        return {
            "arroyo_worker_emit_batch_rows": self.emit_batch_rows,
            "arroyo_worker_queue_transit_seconds": self.queue_transit,
        }[name]

    def add(self, name: str, v: int = 1) -> None:
        self.counters[name] += v

    def backpressure(self) -> float:
        """1 - queue_remaining/queue_size (reference job_metrics.rs:95)."""
        if self.queue_size <= 0:
            return 0.0
        return max(0.0, 1.0 - self.queue_rem / self.queue_size)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: dict[tuple[str, str, int], TaskMetrics] = {}

    def task(self, job_id: str, node_id: str, subtask: int) -> TaskMetrics:
        key = (job_id, node_id, subtask)
        with self._lock:
            tm = self._tasks.get(key)
            if tm is None:
                tm = TaskMetrics(job_id, node_id, subtask)
                self._tasks[key] = tm
            return tm

    def snapshot(self) -> list[TaskMetrics]:
        with self._lock:
            return list(self._tasks.values())

    def clear_job(self, job_id: str) -> None:
        with self._lock:
            self._tasks = {
                k: v for k, v in self._tasks.items() if k[0] != job_id
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format (served at /metrics)."""
        lines: list[str] = []
        tasks = self.snapshot()
        for name in _COUNTER_NAMES:
            lines.append(f"# TYPE {name} counter")
            for t in tasks:
                lines.append(
                    f'{name}{{job="{t.job_id}",operator="{t.node_id}",'
                    f'subtask="{t.subtask}"}} {t.counters[name]}'
                )
        lines.append("# TYPE arroyo_worker_tx_queue_size gauge")
        lines.append("# TYPE arroyo_worker_tx_queue_rem gauge")
        for t in tasks:
            label = (f'job="{t.job_id}",operator="{t.node_id}",'
                     f'subtask="{t.subtask}"')
            lines.append(f"arroyo_worker_tx_queue_size{{{label}}} {t.queue_size}")
            lines.append(f"arroyo_worker_tx_queue_rem{{{label}}} {t.queue_rem}")
        for name in _HISTOGRAM_NAMES:
            lines.append(f"# TYPE {name} histogram")
            for t in tasks:
                h = t.histogram(name)
                if not h.count:
                    continue
                label = (f'job="{t.job_id}",operator="{t.node_id}",'
                         f'subtask="{t.subtask}"')
                cum = 0
                for le, c in zip(h.buckets, h.counts):
                    cum += c
                    lines.append(f'{name}_bucket{{{label},le="{le}"}} {cum}')
                lines.append(f'{name}_bucket{{{label},le="+Inf"}} {h.count}')
                lines.append(f"{name}_sum{{{label}}} {h.sum}")
                lines.append(f"{name}_count{{{label}}} {h.count}")
        return "\n".join(lines) + "\n"

    def job_metrics(self, job_id: str) -> dict:
        """Per-operator aggregates for the API
        (reference /operator_metric_groups)."""
        out: dict[str, dict] = {}
        for t in self.snapshot():
            if t.job_id != job_id:
                continue
            op = out.setdefault(t.node_id, {
                "subtasks": 0,
                **dict.fromkeys(_COUNTER_NAMES, 0),
                "backpressure": 0.0,
                # rate is overwritten by the controller's windowed tracker
                # while the job runs; a terminal snapshot reports 0 so the
                # field contract holds for every consumer (UI charts)
                "messages_per_sec": 0.0,
            })
            op["subtasks"] += 1
            for name in _COUNTER_NAMES:
                op[name] += t.counters[name]
            op["backpressure"] = max(op["backpressure"], t.backpressure())
        return out


registry = MetricsRegistry()


class RateTracker:
    """Windowed rate computation (reference job_metrics.rs rate windows)."""

    def __init__(self, window_s: float = 10.0):
        self.window_s = window_s
        self._points: dict[str, list[tuple[float, int]]] = defaultdict(list)

    def observe(self, key: str, value: int, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        pts = self._points[key]
        pts.append((now, value))
        cutoff = now - self.window_s
        while len(pts) > 2 and pts[0][0] < cutoff:
            pts.pop(0)

    def rate(self, key: str) -> float:
        pts = self._points.get(key)
        if not pts or len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return (v1 - v0) / (t1 - t0)
