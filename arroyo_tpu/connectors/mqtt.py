"""MQTT connector: source + sink over a from-scratch MQTT 3.1.1 client.

Reference: crates/arroyo-connectors/src/mqtt (rumqttc source/sink with
configurable QoS). The 3.1.1 wire protocol is implemented here directly —
CONNECT/CONNACK, SUBSCRIBE/SUBACK, PUBLISH (+PUBACK for QoS 1), PINGREQ/
PINGRESP, DISCONNECT — over a socket, keeping the connector dependency-free
for the air-gapped image.

Delivery notes, mirroring the reference: MQTT without persistent sessions
is at-most-once from the pipeline's perspective, so the source checkpoints
no offsets (restore resumes from "now"); the sink publishes at the
configured QoS and, for QoS 1, waits for the broker's PUBACK per batch.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Optional
from urllib.parse import urlparse

from ..batch import Schema
from ..operators.base import Operator, SourceOperator
from ..types import SourceFinishType
from . import register_sink, register_source

CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK = 8, 9
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


def _encode_len(n: int) -> bytes:
    out = bytearray()
    while True:
        d = n % 128
        n //= 128
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _utf8(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


class MqttClient:
    """Minimal MQTT 3.1.1 client."""

    def __init__(self, host: str, port: int = 1883, client_id: str = "arroyo-tpu",
                 username: Optional[str] = None, password: Optional[str] = None,
                 timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""
        self._pkt_id = 0
        flags = 0x02  # clean session
        payload = _utf8(client_id)
        if username is not None:
            flags |= 0x80
            payload += _utf8(username)
            if password is not None:
                flags |= 0x40
                payload += _utf8(password)
        var = _utf8("MQTT") + bytes([4, flags]) + struct.pack(">H", 60)  # keepalive
        self._send(CONNECT, 0, var + payload)
        ptype, _fl, body = self._read_packet()
        if ptype != CONNACK or len(body) < 2 or body[1] != 0:
            raise ConnectionError(f"MQTT CONNACK refused: {body!r}")

    # ----------------------------------------------------------------- wire

    def _send(self, ptype: int, flags: int, body: bytes) -> None:
        self.sock.sendall(bytes([(ptype << 4) | flags]) + _encode_len(len(body)) + body)

    def _fill(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("MQTT connection closed")
        self.buf += chunk

    def _read_packet(self) -> tuple[int, int, bytes]:
        """Parse one packet, consuming the buffer only once it is complete —
        a socket timeout mid-packet leaves every buffered byte in place, so
        the stream never desyncs."""
        while True:
            parsed = self._try_parse()
            if parsed is not None:
                return parsed
            self._fill()  # raises socket.timeout when idle

    def _try_parse(self) -> Optional[tuple[int, int, bytes]]:
        buf = self.buf
        if len(buf) < 2:
            return None
        h = buf[0]
        n, mult, i = 0, 1, 1
        while True:
            if i >= len(buf):
                return None
            d = buf[i]
            n += (d & 0x7F) * mult
            i += 1
            if not (d & 0x80):
                break
            if mult > 128 ** 3:
                raise ConnectionError("MQTT malformed remaining length")
            mult *= 128
        if len(buf) < i + n:
            return None
        body = buf[i:i + n]
        self.buf = buf[i + n:]
        return h >> 4, h & 0x0F, body

    def _next_id(self) -> int:
        self._pkt_id = self._pkt_id % 65535 + 1
        return self._pkt_id

    # ------------------------------------------------------------------ ops

    def subscribe(self, topic: str, qos: int = 0) -> None:
        pid = self._next_id()
        self._send(SUBSCRIBE, 0x02, struct.pack(">H", pid) + _utf8(topic) + bytes([qos]))
        ptype, _fl, body = self._read_packet()
        if ptype != SUBACK or body[2] & 0x80:
            raise ConnectionError(f"MQTT SUBACK refused: {body!r}")

    def publish(self, topic: str, payload: bytes, qos: int = 0) -> Optional[int]:
        var = _utf8(topic)
        pid = None
        if qos:
            pid = self._next_id()
            var += struct.pack(">H", pid)
        self._send(PUBLISH, qos << 1, var + payload)
        return pid

    def wait_puback(self, pid: int) -> None:
        while True:
            ptype, _fl, body = self._read_packet()
            if ptype == PUBACK and struct.unpack(">H", body[:2])[0] == pid:
                return
            if ptype == PINGREQ:
                self._send(PINGRESP, 0, b"")

    def next_publish(self) -> Optional[tuple[str, bytes]]:
        """One inbound packet; (topic, payload) for PUBLISH, None otherwise.
        Raises socket.timeout when idle."""
        ptype, flags, body = self._read_packet()
        if ptype == PUBLISH:
            tlen = struct.unpack(">H", body[:2])[0]
            topic = body[2:2 + tlen].decode()
            off = 2 + tlen
            qos = (flags >> 1) & 0x03
            if qos:
                pid = struct.unpack(">H", body[off:off + 2])[0]
                off += 2
                self._send(PUBACK, 0, struct.pack(">H", pid))
            return topic, body[off:]
        if ptype == PINGREQ:
            self._send(PINGRESP, 0, b"")
        return None

    def ping(self) -> None:
        self._send(PINGREQ, 0, b"")

    def close(self) -> None:
        try:
            self._send(DISCONNECT, 0, b"")
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def _endpoint(cfg: dict) -> tuple[str, int]:
    url = str(cfg.get("url", "mqtt://127.0.0.1:1883"))
    u = urlparse(url if "://" in url else f"mqtt://{url}")
    return u.hostname or "127.0.0.1", u.port or 1883


class MqttSource(SourceOperator):
    """config: url (mqtt://host:port), topic, qos (0|1), username/password,
    schema + format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.topic = str(cfg["topic"])
        self.qos = int(cfg.get("qos", 0))

    # no state tables: this source is non-replayable (no seekable
    # offset), so there is nothing to snapshot — LR203 rejects a
    # declared-but-unwired TableSpec

    def run(self, sctx, collector) -> SourceFinishType:
        ctx = sctx.ctx
        if ctx.task_info.subtask_index != 0:
            # MQTT subscriptions are fan-out: one reading subtask avoids
            # duplicate delivery (reference uses shared subscriptions only
            # on MQTT 5 brokers)
            return SourceFinishType.GRACEFUL
        host, port = _endpoint(self.cfg)
        client = MqttClient(
            host, port,
            # unique per operator + subtask: duplicate client ids make a
            # compliant broker disconnect the existing session
            client_id=(f"arroyo-{ctx.task_info.job_id[:10]}-"
                       f"{ctx.task_info.node_id[:8]}-{ctx.task_info.subtask_index}"),
            username=self.cfg.get("username"), password=self.cfg.get("password"),
        )
        client.subscribe(self.topic, self.qos)
        client.sock.settimeout(0.2)
        from .broker_base import run_broker_source

        def next_message():
            got = client.next_publish()
            return None if got is None else got[1]

        return run_broker_source(sctx, collector, self.cfg, self.schema,
                                 next_message, client.close,
                                 keepalive=client.ping)


class MqttSink(Operator):
    """config: url, topic, qos (0|1), username/password, schema + format."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.topic = str(cfg["topic"])
        self.qos = int(cfg.get("qos", 0))
        self.client: Optional[MqttClient] = None

    def on_start(self, ctx):
        host, port = _endpoint(self.cfg)
        self.client = MqttClient(
            host, port,
            client_id=f"arroyo-sink-{ctx.task_info.job_id[:10]}-{ctx.task_info.subtask_index}",
            username=self.cfg.get("username"), password=self.cfg.get("password"),
        )

    def drain_inbound(self) -> None:
        """Answer broker PINGREQs between batches without blocking (idle
        sinks must keep the keepalive contract too)."""
        assert self.client is not None
        old = self.client.sock.gettimeout()
        self.client.sock.settimeout(0.0)
        try:
            while True:
                p = self.client._try_parse()
                if p is None:
                    try:
                        self.client._fill()
                    except (BlockingIOError, TimeoutError, socket.timeout):
                        return
                    continue
                ptype, _fl, _body = p
                if ptype == PINGREQ:
                    self.client._send(PINGRESP, 0, b"")
        finally:
            self.client.sock.settimeout(old)

    def handle_tick(self, ctx, collector):
        if self.client is not None:
            self.client.ping()
            self.drain_inbound()

    def tick_interval_micros(self):
        return 20_000_000  # keepalive ping cadence (negotiated 60s)

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..formats.registry import serialize_batch

        assert self.client is not None
        self.drain_inbound()
        last_pid = None
        for payload in serialize_batch(self.cfg, batch, self.cfg.get("schema")):
            last_pid = self.client.publish(self.topic, payload, self.qos)
        if self.qos and last_pid is not None:
            # batch-level acknowledgement: the broker processes in order, so
            # the last PUBACK covers the batch (reference awaits rumqttc acks)
            self.client.wait_puback(last_pid)

    def on_close(self, ctx, collector):
        if self.client is not None:
            self.client.close()


register_source("mqtt")(MqttSource)
register_sink("mqtt")(MqttSink)
