"""Shared poll loop for broker-style sources (NATS, MQTT).

One implementation of the control/checkpoint/flush cycle the reference
repeats per broker connector: poll control (checkpoint/stop), pull one
message from the client, feed the deserializer, flush on batch boundaries
and idle timeouts, and send a keepalive when the link has been quiet.
"""

from __future__ import annotations

import socket
import time
from typing import Callable, Optional

from ..types import SourceFinishType


def run_broker_source(
    sctx,
    collector,
    cfg: dict,
    schema,
    next_message: Callable[[], Optional[bytes]],
    close: Callable[[], None],
    keepalive: Optional[Callable[[], None]] = None,
    keepalive_interval_s: float = 20.0,
) -> SourceFinishType:
    """next_message(): one payload or None (non-message protocol op);
    raises socket.timeout when idle and ConnectionError when the broker is
    gone (treated as end-of-stream, matching the reference's non-replayable
    broker sources)."""
    from ..formats.registry import make_deserializer

    de = make_deserializer(cfg, schema, task_info=sctx.ctx.task_info)
    last_sent = time.monotonic()

    def flush():
        b = de.flush()
        if b is not None:
            collector.collect(b)

    while True:
        # keepalive is a CLIENT-to-server obligation (MQTT-3.1.2-24): inbound
        # traffic does not reset the broker's timer, so ping on cadence
        # regardless of how busy the subscription is
        if keepalive is not None and time.monotonic() - last_sent > keepalive_interval_s:
            try:
                keepalive()
            except OSError:
                flush()
                return SourceFinishType.GRACEFUL
            last_sent = time.monotonic()
        msg = sctx.poll_control()
        if msg is not None:
            if msg.kind == "checkpoint":
                flush()
                sctx.start_checkpoint(msg.barrier)
                if msg.barrier.then_stop:
                    close()
                    return SourceFinishType.FINAL
            elif msg.kind == "stop":
                close()
                return SourceFinishType.IMMEDIATE
        try:
            payload = next_message()
        except (TimeoutError, socket.timeout):
            if de.should_flush():
                flush()
            continue
        except ConnectionError:
            flush()
            return SourceFinishType.GRACEFUL
        if payload is None:
            continue
        de.deserialize(payload, timestamp_micros=int(time.time() * 1e6))
        if de.should_flush():
            flush()
