"""Redis connector: sink + lookup, over a from-scratch RESP client.

Reference: crates/arroyo-connectors/src/redis (sink with string/list/hash
targets; also usable as a lookup table). No client library needed — RESP2 is
a trivial line protocol, spoken here directly over a socket, which also
keeps the connector dependency-free for the air-gapped image.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

from ..operators.base import Operator, TableSpec
from . import register_sink


class RespClient:
    """Minimal RESP2 client (inline pipelining, no pubsub)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379, timeout: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- wire ----------------------------------------------------------------

    @staticmethod
    def encode(*args) -> bytes:
        out = [f"*{len(args)}\r\n".encode()]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(f"${len(b)}\r\n".encode())
            out.append(b)
            out.append(b"\r\n")
        return b"".join(out)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2 :]
        return data

    def _read_reply(self):
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RuntimeError(f"redis error: {rest.decode()}")
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n)
        if t == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RuntimeError(f"unexpected RESP type {t!r}")

    def command(self, *args):
        with self._lock:
            self.sock.sendall(self.encode(*args))
            return self._read_reply()

    def pipeline(self, commands: list[tuple]) -> list:
        with self._lock:
            self.sock.sendall(b"".join(self.encode(*c) for c in commands))
            return [self._read_reply() for _ in commands]


class RedisSink(Operator):
    """config: host, port, target: 'string'|'list'|'hash', key_prefix,
    key_field (column used as the redis key suffix), format options.
    Rows serialize with the configured format (default json)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.host = str(cfg.get("host", "127.0.0.1"))
        self.port = int(cfg.get("port", 6379))
        self.target = str(cfg.get("target", "string"))
        self.key_prefix = str(cfg.get("key_prefix", ""))
        self.key_field = cfg.get("key_field")
        self.schema = cfg.get("schema")
        self.client: Optional[RespClient] = None

    def tables(self):
        return []

    def on_start(self, ctx):
        self.client = RespClient(self.host, self.port)

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..formats.registry import serialize_batch

        payloads = serialize_batch(self.cfg, batch, self.schema)
        keys: list[str]
        if self.key_field and self.key_field in batch:
            keys = [f"{self.key_prefix}{v}" for v in batch[self.key_field]]
        else:
            keys = [self.key_prefix or "arroyo-tpu"] * len(payloads)
        cmds = []
        for k, p in zip(keys, payloads):
            if self.target == "string":
                cmds.append(("SET", k, p))
            elif self.target == "list":
                cmds.append(("RPUSH", k, p))
            elif self.target == "hash":
                cmds.append(("HSET", k, "value", p))
            else:
                raise ValueError(f"unknown redis target {self.target!r}")
        if cmds:
            self.client.pipeline(cmds)

    def on_close(self, ctx, collector):
        if self.client:
            self.client.close()


class RedisLookup:
    """Lookup-table side (LookupJoin `connector` object): GET per key,
    values decoded as JSON objects."""

    def __init__(self, cfg: dict):
        self.client = RespClient(
            str(cfg.get("host", "127.0.0.1")), int(cfg.get("port", 6379))
        )
        self.key_prefix = str(cfg.get("key_prefix", ""))

    def lookup(self, keys: list) -> dict:
        import json

        replies = self.client.pipeline(
            [("GET", f"{self.key_prefix}{k}") for k in keys]
        )
        out = {}
        for k, r in zip(keys, replies):
            out[k] = None if r is None else json.loads(r)
        return out


register_sink("redis")(RedisSink)
