"""In-memory vector sink for tests (collects rows into a shared list)."""

from __future__ import annotations

import threading

from ..operators.base import Operator
from . import register_sink


class VecSink(Operator):
    """config: rows: list (shared, appended under a lock),
    include_internal: bool (keep _timestamp/_key columns),
    columnar: bool (append Batch objects instead of row dicts — no
    per-row materialization cost; used by bench.py)."""

    def __init__(self, cfg: dict):
        self.rows: list = cfg["rows"]  # state: ephemeral — test sink appends to a caller-owned list; at-least-once by contract
        self.include_internal = cfg.get("include_internal", False)
        self.columnar = cfg.get("columnar", False)
        # optional shared list: wall_monotonic per appended batch (columnar
        # mode) — the arrival half of the watermark-to-emit latency metric
        self.arrival_walls: list | None = cfg.get("arrival_walls")  # state: ephemeral — bench-only wall-clock probe list
        self._lock = cfg.setdefault("_lock", threading.Lock())

    def process_batch(self, batch, ctx, collector, input_index=0):
        out = batch
        if not self.include_internal:
            drop = [n for n in batch.columns if n.startswith("_")]
            if drop:
                out = batch.without_columns(drop)
        with self._lock:
            if self.columnar:
                self.rows.append(out)
                if self.arrival_walls is not None:
                    import time

                    self.arrival_walls.append(time.monotonic())
            else:
                self.rows.extend(out.to_pylist())


register_sink("vec")(VecSink)
