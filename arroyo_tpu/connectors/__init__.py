"""Connector registry (reference crates/arroyo-connectors/src/lib.rs:37).

Source/sink constructors dispatch on the ``connector`` key of the node
config. Each connector module registers itself on import.
"""

from __future__ import annotations

from typing import Callable

from ..engine.engine import register_operator
from ..graph import OpName

_SOURCES: dict[str, Callable[[dict], object]] = {}
_SINKS: dict[str, Callable[[dict], object]] = {}


def register_source(name: str):
    def deco(fn):
        _SOURCES[name] = fn
        return fn

    return deco


def register_sink(name: str):
    def deco(fn):
        _SINKS[name] = fn
        return fn

    return deco


@register_operator(OpName.SOURCE)
def _make_source(cfg: dict):
    name = cfg["connector"]
    if name not in _SOURCES:
        raise ValueError(f"unknown source connector {name!r} (have {sorted(_SOURCES)})")
    return _SOURCES[name](cfg)


@register_operator(OpName.SINK)
def _make_sink(cfg: dict):
    name = cfg["connector"]
    if name not in _SINKS:
        raise ValueError(f"unknown sink connector {name!r} (have {sorted(_SINKS)})")
    return _SINKS[name](cfg)


def load_all() -> None:
    from . import blackhole, impulse, single_file, stdout, vec  # noqa: F401
    from . import nexmark  # noqa: F401
    from . import filesystem, http_conn, kafka, preview, redis  # noqa: F401
    from . import kinesis, mqtt, nats, rabbitmq, stubs, websocket  # noqa: F401


def connectors() -> dict:
    load_all()
    return {"sources": sorted(_SOURCES), "sinks": sorted(_SINKS)}
