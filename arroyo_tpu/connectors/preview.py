"""Preview sink: streams output rows back to the controller.

Reference: the preview connector (crates/arroyo-connectors, preview sink)
whose rows reach the controller via the SendSinkData gRPC and feed the UI's
live results pane. Here rows land in a bounded in-process registry; the
worker main loop / embedded handle drains it into `sink_data` events, which
the JobController persists to the shared DB for the API to serve.
"""

from __future__ import annotations

import threading
from collections import deque

from ..formats.json_fmt import serialize_json_lines
from ..operators.base import Operator
from . import register_sink

_LOCK = threading.Lock()
_OUTPUTS: dict[str, deque] = {}
_CAP = 10_000  # rows retained per job (reference bounds preview output too)


def take_preview_rows(job_id: str) -> list[str]:
    """Drain buffered preview rows (JSON strings) for a job."""
    with _LOCK:
        q = _OUTPUTS.get(job_id)
        if not q:
            return []
        out = list(q)
        q.clear()
        return out


class PreviewSink(Operator):
    """config: rows (optional list collecting parsed rows, used by the
    planner for bare-SELECT results in-process)."""

    def __init__(self, cfg: dict):
        self.rows = cfg.get("rows")  # state: ephemeral — debug sink shares a caller-owned list; at-least-once by contract
        self.schema = cfg.get("schema")

    def process_batch(self, batch, ctx, collector, input_index=0):
        lines = serialize_json_lines(batch, self.schema)
        job = ctx.task_info.job_id
        with _LOCK:
            q = _OUTPUTS.setdefault(job, deque(maxlen=_CAP))
            q.extend(lines)
        if self.rows is not None:
            self.rows.extend(batch.to_pylist())


register_sink("preview")(PreviewSink)
