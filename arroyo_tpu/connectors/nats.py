"""NATS connector: source + sink over a from-scratch client.

Reference: crates/arroyo-connectors/src/nats (core-NATS subject source and
sink via async-nats). Core NATS is a line-oriented text protocol (INFO/
CONNECT/SUB/PUB/MSG/PING/PONG), spoken here directly over a socket — no
client library, keeping the connector dependency-free for the air-gapped
image (same approach as the websocket/redis connectors).

Delivery notes, mirroring the reference: core NATS is at-most-once fan-out
with no replay, so the source checkpoints no offsets (a restore resumes
from "now", exactly like the reference's non-JetStream path) and the sink
is fire-and-forget per row.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Optional

from ..batch import Schema
from ..operators.base import Operator, SourceOperator
from ..types import SourceFinishType
from . import register_sink, register_source


class NatsClient:
    """Minimal core-NATS client: connect, subscribe, publish, read MSGs."""

    def __init__(self, host: str = "127.0.0.1", port: int = 4222,
                 timeout: float = 10.0, name: str = "arroyo-tpu"):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""
        info = self._read_line()  # server greeting
        if not info.startswith(b"INFO "):
            raise ConnectionError(f"not a NATS server: {info[:64]!r}")
        self.server_info = json.loads(info[5:])
        self.sock.sendall(
            b"CONNECT " + json.dumps({
                "verbose": False, "pedantic": False, "name": name,
                "lang": "python", "version": "1.0.0", "protocol": 0,
            }).encode() + b"\r\nPING\r\n"
        )
        # drain until PONG so connect errors surface here
        while True:
            line = self._read_line()
            if line == b"PONG":
                break
            if line.startswith(b"-ERR"):
                raise ConnectionError(f"NATS connect rejected: {line.decode()}")

    def _fill(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("NATS connection closed")
        self.buf += chunk

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            self._fill()
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _peek_line(self) -> Optional[bytes]:
        """Complete line without consuming (so a timeout mid-message never
        loses already-buffered protocol bytes)."""
        if b"\r\n" not in self.buf:
            return None
        return self.buf.split(b"\r\n", 1)[0]

    def subscribe(self, subject: str, sid: str = "1",
                  queue_group: Optional[str] = None) -> None:
        q = f" {queue_group}" if queue_group else ""
        self.sock.sendall(f"SUB {subject}{q} {sid}\r\n".encode())

    def publish(self, subject: str, payload: bytes) -> None:
        self.sock.sendall(
            f"PUB {subject} {len(payload)}\r\n".encode() + payload + b"\r\n"
        )

    def next_msg(self) -> Optional[tuple[str, bytes]]:
        """One protocol op; (subject, payload) for MSG, None otherwise.
        Raises socket.timeout when idle (caller polls control then). The
        buffer is only consumed once a whole op is present, so a timeout
        mid-frame never desyncs the stream."""
        while True:
            line = self._peek_line()
            if line is None:
                self._fill()  # raises socket.timeout when idle
                continue
            if line.startswith(b"MSG "):
                parts = line.decode().split(" ")
                # MSG <subject> <sid> [reply-to] <#bytes>
                n = int(parts[-1])
                need = len(line) + 2 + n + 2
                if len(self.buf) < need:
                    self._fill()
                    continue
                payload = self.buf[len(line) + 2 : len(line) + 2 + n]
                self.buf = self.buf[need:]
                return parts[1], payload
            # non-MSG op: consume the line
            self.buf = self.buf[len(line) + 2:]
            if line == b"PING":
                self.sock.sendall(b"PONG\r\n")
            elif line.startswith(b"-ERR"):
                raise ConnectionError(f"NATS error: {line.decode()}")
            return None

    def ping(self) -> None:
        self.sock.sendall(b"PING\r\n")

    def drain_server_ops(self) -> None:
        """Answer pending server PINGs / surface -ERR without blocking —
        write-mostly users (the sink) must still service the link or the
        server declares the connection stale."""
        old = self.sock.gettimeout()
        self.sock.settimeout(0.0)
        try:
            while True:
                line = self._peek_line()
                if line is None:
                    try:
                        self._fill()
                    except (BlockingIOError, TimeoutError, socket.timeout):
                        return
                    continue
                if line.startswith(b"MSG "):
                    return  # subscriber data is the reader loop's business
                self.buf = self.buf[len(line) + 2:]
                if line == b"PING":
                    self.sock.sendall(b"PONG\r\n")
                elif line.startswith(b"-ERR"):
                    raise ConnectionError(f"NATS error: {line.decode()}")
        finally:
            self.sock.settimeout(old)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _parse_servers(cfg: dict) -> tuple[str, int]:
    servers = cfg.get("servers", "nats://127.0.0.1:4222")
    first = servers.split(",")[0].strip()
    if "://" in first:
        first = first.split("://", 1)[1]
    host, _, port = first.partition(":")
    return host or "127.0.0.1", int(port or 4222)


class NatsSource(SourceOperator):
    """config: servers ("nats://host:port[,...]"), subject, queue_group
    (optional — NATS-side load balancing across parallel subtasks),
    schema + format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.subject = str(cfg["subject"])
        self.queue_group = cfg.get("queue_group")

    # no state tables: this source is non-replayable (no seekable
    # offset), so there is nothing to snapshot — LR203 rejects a
    # declared-but-unwired TableSpec

    def run(self, sctx, collector) -> SourceFinishType:
        ctx = sctx.ctx
        if ctx.task_info.subtask_index != 0 and not self.queue_group:
            # without a queue group every subscriber sees every message;
            # one subtask reads to avoid duplicates (reference does the same
            # for non-queue subscriptions)
            return SourceFinishType.GRACEFUL
        host, port = _parse_servers(self.cfg)
        client = NatsClient(host, port)
        client.subscribe(self.subject,
                         sid=str(ctx.task_info.subtask_index + 1),
                         queue_group=self.queue_group)
        client.sock.settimeout(0.2)
        from .broker_base import run_broker_source

        def next_message():
            got = client.next_msg()
            return None if got is None else got[1]

        return run_broker_source(sctx, collector, self.cfg, self.schema,
                                 next_message, client.close,
                                 keepalive=client.ping)


class NatsSink(Operator):
    """config: servers, subject, schema + format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.subject = str(cfg["subject"])
        self.client: Optional[NatsClient] = None

    def on_start(self, ctx):
        host, port = _parse_servers(self.cfg)
        self.client = NatsClient(host, port)

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..formats.registry import serialize_batch

        assert self.client is not None
        self.client.drain_server_ops()  # answer PINGs, surface -ERR
        for payload in serialize_batch(self.cfg, batch, self.cfg.get("schema")):
            self.client.publish(self.subject, payload)

    def handle_tick(self, ctx, collector):
        # idle sinks must keep the link serviced too, or the server declares
        # it stale after unanswered PINGs
        if self.client is not None:
            self.client.ping()
            self.client.drain_server_ops()

    def tick_interval_micros(self):
        return 20_000_000

    def on_close(self, ctx, collector):
        if self.client is not None:
            self.client.close()


register_source("nats")(NatsSource)
register_sink("nats")(NatsSink)
