"""Impulse source: synthetic counter stream at a configured rate
(reference crates/arroyo-connectors/src/impulse/mod.rs:104-183).

Schema: counter uint64, subtask_index uint64, _timestamp. Offsets checkpoint
into a global-keyed table so restore resumes exactly where the snapshot was
taken (exactly-once source semantics).

Load-ramp extension (the autoscaler bench's traffic generator):
``rate_phases`` describes a piecewise-constant schedule of total event
rates — e.g. ``"10000x30000,40000"`` = 10k events/s for the first 30k
events, then 40k events/s unbounded (counts and rates are totals across
subtasks, like ``event_rate``). Under a schedule, event ``_timestamp``s
are the *scheduled emission wall time* (the first run's wall clock plus
the schedule offset), so the sink-side event-latency histogram reads
directly as "how far behind schedule is this pipeline" — the signal a 4x
spike melts and a rescale must recover. A wall-clock anchor persists in
the offsets table so restores and rescales stay on ONE schedule, and
every (re)start resumes at the schedule's live edge — a per-subtask
counter means nothing across a parallelism change, so scheduled mode
trades exactly-once replay (the chaos suite's concern, not a load
generator's) for a stable wall-clock rate.

Plain ``event_rate`` mode stays an exactly-once source (counters resume
from the snapshot) but now paces RELATIVE to the resume point: a
restored subtask used to sleep out the entire already-elapsed run before
its next batch (absolute counter against a fresh start time), and a
rescale silently re-meant the counter against the new per-task rate.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..batch import TIMESTAMP_FIELD, Batch, Field, Schema
from ..config import config
from ..operators.base import SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_source

IMPULSE_SCHEMA = Schema.of(
    [Field("counter", "uint64"), Field("subtask_index", "uint64"), Field(TIMESTAMP_FIELD, "int64")]
)

_ANCHOR_KEY = "anchor_us"  # durable pacing anchor in the offsets table


def parse_rate_phases(spec) -> list[tuple[Optional[int], float]]:
    """``"10000x30000,40000"`` -> ``[(30000, 10000.0), (None, 40000.0)]``:
    comma-separated ``RATExCOUNT`` phases (events/s for the next COUNT
    events, totals across subtasks); a bare RATE runs unbounded. Already-
    structured lists of [count, rate] pairs pass through."""
    if isinstance(spec, (list, tuple)):
        return [(None if c is None else int(c), float(r)) for c, r in spec]
    phases: list[tuple[Optional[int], float]] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "x" in part:
            rate, count = part.split("x", 1)
            phases.append((int(float(count)), float(rate)))
        else:
            phases.append((None, float(part)))
    if not phases:
        raise ValueError(f"empty rate_phases spec {spec!r}")
    if phases[-1][0] is not None:
        # the schedule must cover every event number: extend the last rate
        phases.append((None, phases[-1][1]))
    return phases


def _schedule_offsets_us(idx: np.ndarray, phases, parallelism: int) -> np.ndarray:
    """Scheduled emission offset (us from the anchor) for per-subtask
    event indices ``idx``. Each subtask owns 1/p of every phase's count
    and rate, so per-subtask schedules all track the global wall
    schedule."""
    out = np.zeros(len(idx), dtype=np.float64)
    i = idx.astype(np.float64)
    base_i = 0.0
    base_t = 0.0
    for count, rate in phases:
        per_task_rate = max(rate / parallelism, 1e-9)
        if count is None:
            np.copyto(out, base_t + (i - base_i) * 1e6 / per_task_rate,
                      where=i >= base_i)
            break
        span = count / parallelism
        sel = (i >= base_i) & (i < base_i + span)
        np.copyto(out, base_t + (i - base_i) * 1e6 / per_task_rate, where=sel)
        base_t += span * 1e6 / per_task_rate
        base_i += span
    return out


def _schedule_index_at(offset_us: float, phases, parallelism: int) -> int:
    """Inverse of ``_schedule_offsets_us`` for one offset: the per-subtask
    event index scheduled at that moment (a mid-run joiner's live edge)."""
    base_i = 0.0
    base_t = 0.0
    for count, rate in phases:
        per_task_rate = max(rate / parallelism, 1e-9)
        if count is None:
            return int(base_i + max(0.0, offset_us - base_t) * per_task_rate / 1e6)
        span = count / parallelism
        phase_end = base_t + span * 1e6 / per_task_rate
        if offset_us < phase_end:
            return int(base_i + max(0.0, offset_us - base_t) * per_task_rate / 1e6)
        base_t = phase_end
        base_i += span
    return int(base_i)


class ImpulseSource(SourceOperator):
    """config: event_rate (rows/s total, 0 = unthrottled), message_count
    (per subtask; None = unbounded), interval_micros (event-time step;
    default derived from event_rate or 1ms), start_time_micros,
    rate_phases (piecewise rate schedule, see parse_rate_phases)."""

    def __init__(self, cfg: dict):
        self.event_rate = float(cfg.get("event_rate") or 0)
        self.message_count = (None if cfg.get("message_count") is None
                              else int(cfg["message_count"]))
        start = cfg.get("start_time_micros")
        self.start_time_micros = (int(time.time() * 1e6) if start is None
                                  else int(start))
        self.phases = (parse_rate_phases(cfg["rate_phases"])
                       if cfg.get("rate_phases") else None)
        if cfg.get("interval_micros") is not None:
            self.interval_micros = int(cfg["interval_micros"])
        elif self.event_rate:
            self.interval_micros = max(int(1e6 / self.event_rate), 1)
        else:
            self.interval_micros = 1000

    def tables(self):
        return [TableSpec("s", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        p = ctx.task_info.parallelism
        tbl = ctx.table_manager.global_keyed("s")
        batch_size = config().get("pipeline.source-batch-size")
        rate_per_task = self.event_rate / p if self.event_rate else 0
        started = time.monotonic()
        if self.phases is not None:
            # scheduled mode is a LOAD GENERATOR, not an exactly-once
            # source: every (re)start resumes at the schedule's live edge
            # — a per-subtask counter means nothing across a parallelism
            # change (the same index maps to a p-times-different schedule
            # offset), so replaying it would either re-emit the whole
            # stream at full speed or sleep far ahead of schedule.
            # Byte-exact replay is the chaos suite's concern; this source
            # exists to hold a wall-clock rate schedule. The wall anchor
            # (first-run wall us; start_time_micros stays a pure
            # event-time base) persists in the offsets table so restores
            # and rescales keep one schedule.
            anchor_us = tbl.get(_ANCHOR_KEY)
            if anchor_us is None:
                anchor_us = int(time.time() * 1e6)
                tbl.insert(_ANCHOR_KEY, anchor_us)
            now_wall_us = time.time() * 1e6
            started -= max(0.0, (now_wall_us - anchor_us) / 1e6)
            counter = _schedule_index_at(
                max(0.0, now_wall_us - anchor_us), self.phases, p)
        else:
            anchor_us = None
            counter = tbl.get(sub, 0)
        # plain event_rate pacing is RELATIVE to the resume point: a
        # restored subtask continues at the configured rate from where
        # its snapshot left off, instead of sleeping out the entire
        # already-elapsed run against an absolute counter (which also
        # re-means whenever a rescale changes rate_per_task)
        pace_base = counter

        def control() -> Optional[SourceFinishType]:
            msg = sctx.poll_control()
            if msg is None:
                return None
            if msg.kind == "checkpoint":
                tbl.insert(sub, counter)
                sctx.start_checkpoint(msg.barrier)
                if msg.barrier.then_stop:
                    return SourceFinishType.FINAL
            elif msg.kind == "stop":
                return SourceFinishType.IMMEDIATE
            return None

        while self.message_count is None or counter < self.message_count:
            r = control()
            if r is not None:
                return r
            n = batch_size
            if self.message_count is not None:
                n = min(n, self.message_count - counter)
            idx = np.arange(counter, counter + n, dtype=np.uint64)
            if self.phases is not None:
                # scheduled-emission timestamps: latency at the sink reads
                # as "how far behind schedule", the load-ramp bench signal
                offs = _schedule_offsets_us(idx.astype(np.int64), self.phases, p)
                ts = anchor_us + offs.astype(np.int64)
            else:
                ts = self.start_time_micros + idx.astype(np.int64) * self.interval_micros
            collector.collect(
                Batch(
                    {
                        "counter": idx,
                        "subtask_index": np.full(n, sub, dtype=np.uint64),
                        TIMESTAMP_FIELD: ts,
                    }
                )
            )
            counter += n
            if self.phases is not None:
                target = started + _schedule_offsets_us(
                    np.array([counter], dtype=np.int64), self.phases, p)[0] / 1e6
            elif rate_per_task:
                target = started + (counter - pace_base) / rate_per_task
            else:
                continue
            while True:
                delay = target - time.monotonic()
                if delay <= 0:
                    break
                r = control()
                if r is not None:
                    return r
                time.sleep(min(delay, 0.05))
        # keep the offset table current for the run loop's final snapshot
        tbl.insert(sub, counter)
        return SourceFinishType.GRACEFUL


register_source("impulse")(ImpulseSource)
