"""Impulse source: synthetic counter stream at a configured rate
(reference crates/arroyo-connectors/src/impulse/mod.rs:104-183).

Schema: counter uint64, subtask_index uint64, _timestamp. Offsets checkpoint
into a global-keyed table so restore resumes exactly where the snapshot was
taken (exactly-once source semantics).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..batch import TIMESTAMP_FIELD, Batch, Field, Schema
from ..config import config
from ..operators.base import SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_source

IMPULSE_SCHEMA = Schema.of(
    [Field("counter", "uint64"), Field("subtask_index", "uint64"), Field(TIMESTAMP_FIELD, "int64")]
)


class ImpulseSource(SourceOperator):
    """config: event_rate (rows/s, 0 = unthrottled), message_count (per
    subtask; None = unbounded), interval_micros (event-time step; default
    derived from event_rate or 1ms), start_time_micros."""

    def __init__(self, cfg: dict):
        self.event_rate = cfg.get("event_rate", 0)
        self.message_count = cfg.get("message_count")
        self.start_time_micros = cfg.get("start_time_micros", int(time.time() * 1e6))
        if cfg.get("interval_micros") is not None:
            self.interval_micros = cfg["interval_micros"]
        elif self.event_rate:
            self.interval_micros = max(int(1e6 / self.event_rate), 1)
        else:
            self.interval_micros = 1000

    def tables(self):
        return [TableSpec("s", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        tbl = ctx.table_manager.global_keyed("s")
        counter = tbl.get(sub, 0)
        batch_size = config().get("pipeline.source-batch-size")
        rate_per_task = (
            self.event_rate / ctx.task_info.parallelism if self.event_rate else 0
        )
        started = time.monotonic()

        def control() -> Optional[SourceFinishType]:
            msg = sctx.poll_control()
            if msg is None:
                return None
            if msg.kind == "checkpoint":
                tbl.insert(sub, counter)
                sctx.start_checkpoint(msg.barrier)
                if msg.barrier.then_stop:
                    return SourceFinishType.FINAL
            elif msg.kind == "stop":
                return SourceFinishType.IMMEDIATE
            return None

        while self.message_count is None or counter < self.message_count:
            r = control()
            if r is not None:
                return r
            n = batch_size
            if self.message_count is not None:
                n = min(n, self.message_count - counter)
            idx = np.arange(counter, counter + n, dtype=np.uint64)
            ts = self.start_time_micros + idx.astype(np.int64) * self.interval_micros
            collector.collect(
                Batch(
                    {
                        "counter": idx,
                        "subtask_index": np.full(n, sub, dtype=np.uint64),
                        TIMESTAMP_FIELD: ts,
                    }
                )
            )
            counter += n
            if rate_per_task:
                target = started + counter / rate_per_task
                while True:
                    delay = target - time.monotonic()
                    if delay <= 0:
                        break
                    r = control()
                    if r is not None:
                        return r
                    time.sleep(min(delay, 0.05))
        # keep the offset table current for the run loop's final snapshot
        tbl.insert(sub, counter)
        return SourceFinishType.GRACEFUL


register_source("impulse")(ImpulseSource)
