"""Kafka connector: source with exactly-once offsets, transactional sink.

Reference: crates/arroyo-connectors/src/kafka (librdkafka; offsets stored in
state for exactly-once reads; transactional producer with an id per epoch
and a two-phase commit table, sink/mod.rs:142-270).

Gated on the `confluent_kafka` package (librdkafka bindings). The control
flow — offset state, barrier participation, transactional epochs — is
implemented here; without the package, constructing the operator raises with
install instructions (this image is air-gapped, so the path is exercised in
deployments, unit-covered via the _OffsetTracker/_TxnState helpers).
"""

from __future__ import annotations

from typing import Optional

from ..batch import Schema
from ..config import config
from ..operators.base import Operator, SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_sink, register_source


def _auth_conf(cfg: dict) -> dict:
    """librdkafka auth/transport options passed through to the client —
    security.protocol, sasl.*, ssl.* (a Confluent Cloud connection profile
    is exactly bootstrap + SASL_SSL + key/secret; reference
    connectors/src/kafka profiles). 'librdkafka.<opt>' passes any other
    client option verbatim."""
    out = {}
    for k, v in cfg.items():
        if k.startswith(("security.", "sasl.", "ssl.")):
            out[k] = v
        elif k.startswith("librdkafka."):
            out[k[len("librdkafka."):]] = v
    return out


def _require_kafka():
    try:
        import confluent_kafka  # noqa: F401

        return confluent_kafka
    except ImportError as e:
        raise ImportError(
            "the kafka connector requires the 'confluent_kafka' package "
            "(librdkafka bindings): pip install confluent-kafka"
        ) from e


class _OffsetTracker:
    """Partition -> next offset, merged across restores at any parallelism:
    each subtask owns partitions where partition % parallelism == subtask."""

    def __init__(self):
        self.offsets: dict[int, int] = {}

    def observe(self, partition: int, offset: int) -> None:
        cur = self.offsets.get(partition, -1)
        if offset >= cur:
            self.offsets[partition] = offset + 1

    def resume_position(self, partition: int) -> Optional[int]:
        return self.offsets.get(partition)

    def merge(self, other: dict[int, int]) -> None:
        for p, o in other.items():
            if o > self.offsets.get(p, -1):
                self.offsets[p] = o

    def partitions_for(self, subtask: int, parallelism: int, n_partitions: int) -> list[int]:
        return [p for p in range(n_partitions) if p % parallelism == subtask]


class _TxnState:
    """Transactional-sink bookkeeping (reference: transactional id per
    epoch + committing state, kafka/sink/mod.rs:142-155, :252-270)."""

    def __init__(self, job_id: str, node_id: str, subtask: int):
        self.base = f"arroyo-tpu-{job_id}-{node_id}-{subtask}"
        self.epoch: Optional[int] = None

    def txn_id(self, epoch: int) -> str:
        return f"{self.base}-{epoch}"


class KafkaSource(SourceOperator):
    """config: bootstrap_servers, topic, group_id, schema, format options,
    'source.offset' = earliest|latest."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.topic = str(cfg["topic"])
        self.bootstrap = str(cfg.get("bootstrap_servers", "localhost:9092"))
        self.auto_offset = str(cfg.get("source.offset", "earliest"))

    def tables(self):
        return [TableSpec("k", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        ck = _require_kafka()
        from ..formats.registry import make_deserializer

        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        p = ctx.task_info.parallelism
        tbl = ctx.table_manager.global_keyed("k")
        tracker = _OffsetTracker()
        # union offsets saved by EVERY prior subtask: after a rescale,
        # partitions move between subtasks, so resume positions must come
        # from the whole job's offset map, not this subtask's old entry
        # lint: waive LR204 — max-merge of offset maps is order-insensitive
        for _old_sub, saved in tbl.items():
            if saved:
                tracker.merge(saved)
        consumer = ck.Consumer({
            # auth first: operator-managed keys stay authoritative — a
            # pass-through enable.auto.commit=true would silently break the
            # state-based exactly-once contract
            **_auth_conf(self.cfg),
            "bootstrap.servers": self.bootstrap,
            "group.id": str(self.cfg.get("group_id", f"arroyo-tpu-{ctx.task_info.job_id}")),
            "enable.auto.commit": False,
            "auto.offset.reset": self.auto_offset,
        })
        meta = consumer.list_topics(self.topic, timeout=10)
        n_parts = len(meta.topics[self.topic].partitions)
        my_parts = tracker.partitions_for(sub, p, n_parts)
        assignments = []
        for part in my_parts:
            pos = tracker.resume_position(part)
            tp = ck.TopicPartition(self.topic, part)
            if pos is not None:
                tp.offset = pos
            assignments.append(tp)
        consumer.assign(assignments)
        de = make_deserializer(self.cfg, self.schema, task_info=ctx.task_info)
        try:
            while True:
                msg = sctx.poll_control()
                if msg is not None:
                    if msg.kind == "checkpoint":
                        b = de.flush()
                        if b is not None:
                            collector.collect(b)
                        tbl.insert(sub, dict(tracker.offsets))
                        sctx.start_checkpoint(msg.barrier)
                        if msg.barrier.then_stop:
                            return SourceFinishType.FINAL
                    elif msg.kind == "stop":
                        return SourceFinishType.IMMEDIATE
                record = consumer.poll(timeout=0.1)
                if record is None:
                    if de.should_flush():
                        b = de.flush()
                        if b is not None:
                            collector.collect(b)
                    continue
                if record.error():
                    continue
                tracker.observe(record.partition(), record.offset())
                ts_type, ts_ms = record.timestamp()
                ts_us = ts_ms * 1000 if ts_type != ck.TIMESTAMP_NOT_AVAILABLE else None
                de.deserialize(record.value(), timestamp_micros=ts_us)
                if de.should_flush():
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
        finally:
            consumer.close()


class KafkaSink(Operator):
    """config: bootstrap_servers, topic, format options,
    'sink.commit-mode' = at_least_once | exactly_once.

    exactly_once: records buffer in-operator and snapshot into state at the
    barrier (phase 1); the commit phase produces them inside one Kafka
    transaction. A crash between checkpoint and commit restores the buffered
    epoch from state and re-produces it in a fresh transaction — the fenced
    old transaction was aborted by the broker, so the records land exactly
    once. (librdkafka cannot resume a prepared transaction across processes,
    so produce-at-commit is the sound two-phase mapping; the reference keeps
    an open transaction because its worker process owns recovery of the same
    producer, kafka/sink/mod.rs:142-270.)"""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Optional[Schema] = cfg.get("schema")
        self.topic = str(cfg["topic"])
        self.bootstrap = str(cfg.get("bootstrap_servers", "localhost:9092"))
        self.exactly_once = str(cfg.get("sink.commit-mode", "at_least_once")) == "exactly_once"
        self.producer = None
        self.txn: Optional[_TxnState] = None
        self.buf: list[bytes] = []  # exactly-once: payloads since last barrier
        self.pending: dict[int, list[bytes]] = {}  # epoch -> uncommitted payloads

    def tables(self):
        return [TableSpec("p", "global_keyed")]

    def is_committing(self) -> bool:
        return self.exactly_once

    def on_start(self, ctx):
        ck = _require_kafka()
        # auth first: operator-managed keys stay authoritative (matches the
        # consumer's merge order)
        conf = {**_auth_conf(self.cfg), "bootstrap.servers": self.bootstrap}
        if self.exactly_once:
            ti = ctx.task_info
            self.txn = _TxnState(ti.job_id, ti.node_id, ti.subtask_index)
            # stable transactional id: a post-restart producer with the same
            # id fences (and aborts) the zombie from the failed run
            conf["transactional.id"] = self.txn.base
        self.producer = ck.Producer(conf)
        if self.exactly_once:
            self.producer.init_transactions(10)
            saved = ctx.table_manager.global_keyed("p").get(ctx.task_info.subtask_index)
            if saved:
                self.pending = {int(e): list(p) for e, p in saved.get("pending", [])}
                # crash between checkpoint and commit: the old txn was
                # aborted by fencing, so re-produce + commit now
                for epoch in sorted(self.pending):
                    self._commit_epoch(epoch, ctx)

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..formats.registry import serialize_batch

        payloads = serialize_batch(self.cfg, batch, self.schema)
        if self.exactly_once:
            self.buf.extend(payloads)
            return
        for payload in payloads:
            # effect: idempotent — at_least_once mode only (the exactly_once path returned above: it buffers and produces under handle_commit); duplicates on replay are that mode's contract
            self.producer.produce(self.topic, payload)
        self.producer.poll(0)

    def handle_checkpoint(self, barrier, ctx, collector):
        if not self.exactly_once:
            self.producer.flush(30)
            return
        # phase 1: stage this epoch's records durably
        if self.buf:
            self.pending[barrier.epoch] = self.buf
            self.buf = []
        ctx.table_manager.global_keyed("p").insert(
            ctx.task_info.subtask_index,
            {"pending": [(e, list(p)) for e, p in self.pending.items()]},
        )

    def handle_commit(self, epoch, ctx):
        if self.exactly_once:
            self._commit_epoch(epoch, ctx)

    def _marker_path(self, epoch: int, ctx) -> str:
        import os

        from ..state import storage

        ti = ctx.task_info
        d = os.path.join(ctx.table_manager.storage_url, ti.job_id, "commits")
        storage.makedirs(d)
        return os.path.join(d, f"{ti.node_id}-{ti.subtask_index:03d}-{epoch:07d}.done")

    def _commit_epoch(self, epoch: int, ctx) -> None:
        from ..state import storage

        payloads = self.pending.pop(epoch, None)
        if payloads is None:
            return
        if storage.exists(self._marker_path(epoch, ctx)):
            return  # committed in a previous incarnation; don't re-produce
        if payloads:
            self.producer.begin_transaction()
            for p in payloads:
                self.producer.produce(self.topic, p)
            self.producer.commit_transaction(30)
        # durable commit marker NOW (not at the next barrier): a crash after
        # commit_transaction but before the next checkpoint must not
        # re-produce this epoch on restore. (The marker-write itself leaves
        # a sub-millisecond window after broker commit — the unavoidable 2PC
        # residue without broker-side transaction resumption.)
        # markers live on the shared checkpoint store (durable + visible to a
        # worker restarted on another machine), not the local disk
        storage.write_text(self._marker_path(epoch, ctx), "committed")
        ctx.table_manager.global_keyed("p").insert(
            ctx.task_info.subtask_index,
            {"pending": [(e, list(p)) for e, p in self.pending.items()]},
        )

    def on_close(self, ctx, collector):
        if self.producer is None:
            return
        if self.exactly_once:
            # graceful drain: commit whatever remains (idempotence not
            # needed — this is the only writer for these epochs now)
            for epoch in sorted(self.pending):
                self._commit_epoch(epoch, ctx)
            if self.buf:
                self.producer.begin_transaction()
                for p in self.buf:
                    self.producer.produce(self.topic, p)
                self.producer.commit_transaction(30)
                self.buf = []
        self.producer.flush(30)


register_source("kafka")(KafkaSource)
register_sink("kafka")(KafkaSink)
