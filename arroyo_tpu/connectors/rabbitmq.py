"""RabbitMQ connector: source + sink over a from-scratch AMQP 0-9-1 client.

Reference: crates/arroyo-connectors/src/rabbitmq (lapin-based queue source
and exchange sink). AMQP 0-9-1 is a framed binary protocol — protocol
header, then method/content-header/content-body frames on channels — spoken
here directly over a socket (no pika), the same dependency-free approach as
the MQTT/NATS connectors.

Subset implemented: PLAIN auth handshake (Connection Start/Tune/Open),
channel open, Queue.Declare, Basic.Publish (content header + single body
frame per message), Basic.Consume/Deliver with per-message Basic.Ack, and
heartbeat frames both ways. Delivery is at-least-once: delivery tags are
held keyed by checkpoint epoch and acked only when the engine's COMMIT
control message confirms that epoch's checkpoint is durable (the same
two-phase flow the exactly-once Kafka sink uses) — a crash at any point
before the commit leaves the tags unacked, so the broker redelivers.

Options: host, port (5672), username/password (guest/guest), vhost (/),
queue (source), exchange + routing_key (sink; default exchange when empty).
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Optional

from ..batch import Schema
from ..operators.base import Operator, SourceOperator
from ..types import SourceFinishType
from . import register_sink, register_source

FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">B", len(b)) + b


def _longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


class AmqpClient:
    """Minimal AMQP 0-9-1 client on channel 1."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5672,
                 username: str = "guest", password: str = "guest",
                 vhost: str = "/", timeout: float = 10.0,
                 heartbeat: Optional[int] = None):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""
        self.heartbeat = 0
        self.sock.sendall(b"AMQP\x00\x00\x09\x01")
        # Connection.Start
        cid, mid, _args = self._expect_method(10, 10)
        # Start-Ok: client-properties(table) mechanism response locale
        plain = b"\x00" + username.encode() + b"\x00" + password.encode()
        self._send_method(0, 10, 11, _longstr(b"") + _shortstr("PLAIN")
                          + _longstr(plain) + _shortstr("en_US"))
        # Tune; a write-mostly client (the sink) negotiates heartbeat=0 so
        # the broker never expects frames on a quiet stream
        _c, _m, args = self._expect_method(10, 30)
        channel_max, frame_max, hb_server = struct.unpack(">HIH", args[:8])
        self.frame_max = frame_max or 131072
        self.heartbeat = hb_server if heartbeat is None else heartbeat
        self._send_method(0, 10, 31, struct.pack(
            ">HIH", channel_max, self.frame_max, self.heartbeat))
        # Open (vhost, reserved shortstr, reserved bit)
        self._send_method(0, 10, 40, _shortstr(vhost) + _shortstr("") + b"\x00")
        self._expect_method(10, 41)
        # Channel.Open
        self._send_method(1, 20, 10, _shortstr(""))
        self._expect_method(20, 11)
        self._last_sent = time.monotonic()

    # ------------------------------------------------------------- framing

    def _fill(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("AMQP connection closed")
        self.buf += chunk

    def _read_frame(self) -> tuple[int, int, bytes]:
        """(type, channel, payload); raises socket.timeout when idle with
        nothing buffered (partial frames stay buffered, never desync)."""
        while len(self.buf) < 7:
            self._fill()
        ftype, channel, size = struct.unpack(">BHI", self.buf[:7])
        while len(self.buf) < 7 + size + 1:
            self._fill()
        payload = self.buf[7:7 + size]
        if self.buf[7 + size] != FRAME_END:
            raise ConnectionError("AMQP framing error (bad frame-end)")
        self.buf = self.buf[7 + size + 1:]
        return ftype, channel, payload

    def _send_frame(self, ftype: int, channel: int, payload: bytes) -> None:
        self.sock.sendall(struct.pack(">BHI", ftype, channel, len(payload))
                          + payload + bytes([FRAME_END]))
        self._last_sent = time.monotonic()

    def _send_method(self, channel: int, cid: int, mid: int, args: bytes) -> None:
        self._send_frame(FRAME_METHOD, channel, struct.pack(">HH", cid, mid) + args)

    def _expect_method(self, cid: int, mid: int) -> tuple[int, int, bytes]:
        while True:
            ftype, _ch, payload = self._read_frame()
            if ftype == FRAME_HEARTBEAT:
                self._send_frame(FRAME_HEARTBEAT, 0, b"")
                continue
            if ftype != FRAME_METHOD:
                continue
            c, m = struct.unpack(">HH", payload[:4])
            if (c, m) == (10, 50) or (c, m) == (20, 40):  # Connection/Channel.Close
                code = struct.unpack(">H", payload[4:6])[0]
                raise ConnectionError(f"AMQP close: code {code}")
            if (c, m) != (cid, mid):
                continue
            return c, m, payload[4:]

    # ------------------------------------------------------------- methods

    def queue_declare(self, queue: str, durable: bool = False) -> None:
        bits = 0x02 if durable else 0x00
        self._send_method(1, 50, 10, struct.pack(">H", 0) + _shortstr(queue)
                          + bytes([bits]) + _longstr(b""))
        self._expect_method(50, 11)

    def publish(self, exchange: str, routing_key: str, body: bytes) -> None:
        self._send_method(1, 60, 40, struct.pack(">H", 0) + _shortstr(exchange)
                          + _shortstr(routing_key) + b"\x00")
        # content header: class 60, weight 0, body size, no properties
        self._send_frame(FRAME_HEADER, 1,
                         struct.pack(">HHQH", 60, 0, len(body), 0))
        cap = self.frame_max - 8
        for i in range(0, len(body), cap):
            self._send_frame(FRAME_BODY, 1, body[i:i + cap])

    def consume(self, queue: str) -> None:
        # no-local=0 no-ack=0 exclusive=0 no-wait=0
        self._send_method(1, 60, 20, struct.pack(">H", 0) + _shortstr(queue)
                          + _shortstr("") + b"\x00" + _longstr(b""))
        self._expect_method(60, 21)

    def ack(self, delivery_tag: int) -> None:
        self._send_method(1, 60, 80, struct.pack(">QB", delivery_tag, 0))

    def _peek_frame(self, off: int) -> Optional[tuple[int, int, bytes, int]]:
        """Frame at buffer offset ``off`` without consuming:
        (type, channel, payload, next_off), or None when incomplete."""
        if len(self.buf) < off + 7:
            return None
        ftype, channel, size = struct.unpack(">BHI", self.buf[off:off + 7])
        end = off + 7 + size + 1
        if len(self.buf) < end:
            return None
        if self.buf[end - 1] != FRAME_END:
            raise ConnectionError("AMQP framing error (bad frame-end)")
        return ftype, channel, self.buf[off + 7:end - 1], end

    def next_delivery(self) -> Optional[tuple[int, bytes]]:
        """(delivery_tag, body) for one Basic.Deliver, None for other
        protocol traffic; raises socket.timeout when idle. A Deliver's
        method/header/body frame group is consumed ATOMICALLY: nothing is
        taken off the buffer until the whole group is present, so a read
        timeout mid-group never drops a message (at-least-once holds)."""
        got = self._peek_frame(0)
        if got is None:
            self._fill()  # raises socket.timeout when idle
            return None
        ftype, _ch, payload, end = got
        if ftype == FRAME_HEARTBEAT:
            self.buf = self.buf[end:]
            self._send_frame(FRAME_HEARTBEAT, 0, b"")
            return None
        if ftype != FRAME_METHOD:
            self.buf = self.buf[end:]
            return None
        c, m = struct.unpack(">HH", payload[:4])
        if (c, m) == (10, 50) or (c, m) == (20, 40):
            raise ConnectionError("AMQP close from server")
        if (c, m) != (60, 60):  # Basic.Deliver
            self.buf = self.buf[end:]
            return None
        off = 4
        taglen = payload[off]
        off += 1 + taglen  # consumer-tag
        (delivery_tag,) = struct.unpack(">Q", payload[off:off + 8])
        off += 8 + 1  # redelivered bit
        exlen = payload[off]
        off += 1 + exlen  # exchange
        rklen = payload[off]
        off += 1 + rklen  # routing key
        # content header frame (peek; do not consume yet)
        got = self._peek_frame(end)
        if got is None:
            self._fill()
            return None  # whole group still buffered; retry next call
        ftype, _ch, hpayload, end = got
        if ftype != FRAME_HEADER:
            raise ConnectionError("AMQP: expected content header")
        (_cls, _w, body_size) = struct.unpack(">HHQ", hpayload[:12])
        body = b""
        while len(body) < body_size:
            got = self._peek_frame(end)
            if got is None:
                self._fill()
                return None  # retry with more bytes buffered
            ftype, _ch, bpayload, end = got
            if ftype != FRAME_BODY:
                raise ConnectionError("AMQP: expected content body")
            body += bpayload
        self.buf = self.buf[end:]  # consume the whole group at once
        return delivery_tag, body

    def send_heartbeat(self) -> None:
        self._send_frame(FRAME_HEARTBEAT, 0, b"")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _client_from(cfg: dict, heartbeat: Optional[int] = None) -> AmqpClient:
    return AmqpClient(
        host=str(cfg.get("host", "127.0.0.1")),
        port=int(cfg.get("port", 5672)),
        username=str(cfg.get("username", "guest")),
        password=str(cfg.get("password", "guest")),
        vhost=str(cfg.get("vhost", "/")),
        heartbeat=heartbeat,
    )


@register_source("rabbitmq")
class RabbitmqSource(SourceOperator):
    """config: host, port, queue, username/password, vhost,
    schema + format options. Parallel subtasks share the queue: AMQP
    round-robins deliveries across consumers, so every subtask consumes."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.queue = str(cfg["queue"])

    def is_committing(self) -> bool:
        # acks are phase 2 of the checkpoint: the engine must send this
        # source a commit message once the epoch's metadata is durable
        return True

    def run(self, sctx, collector) -> SourceFinishType:
        """Commit-deferred acks: tags collect as messages reach the
        deserializer; a checkpoint barrier moves the batch under its epoch,
        and the batch acks only when the engine's post-checkpoint COMMIT for
        that epoch arrives. A crash mid-checkpoint (barrier seen, metadata
        not yet durable) therefore leaves the tags unacked and the broker
        redelivers after restore — at-least-once holds through the exact
        window where barrier-time acking used to lose data."""
        import socket as _socket
        import time as _time

        from ..faults import InjectedFault, fault_point
        from ..formats.registry import make_deserializer
        from ..utils.retry import Backoff, RetryPolicy, retry_call

        client = _client_from(self.cfg)
        client.queue_declare(self.queue)
        client.consume(self.queue)
        client.sock.settimeout(0.2)
        de = make_deserializer(self.cfg, self.schema,
                               task_info=sctx.ctx.task_info)
        pending_tags: list[int] = []        # delivered since the last barrier
        tags_by_epoch: dict[int, list[int]] = {}  # barrier-taken, ack on commit
        ka_interval = client.heartbeat / 2 if client.heartbeat else 20.0
        last_sent = _time.monotonic()
        poll_backoff = Backoff(RetryPolicy(max_attempts=1 << 30,
                                           base_delay_s=0.05, max_delay_s=1.0))

        def flush():
            b = de.flush()
            if b is not None:
                collector.collect(b)

        def ack_through(epoch: int) -> None:
            """Ack every epoch <= the committed one (a straggling commit for
            an older epoch must not strand its tags forever)."""
            for ep in sorted(e for e in tags_by_epoch if e <= epoch):
                tags = tags_by_epoch.pop(ep)

                def _ack_remaining(_tags=tags, _ep=ep):
                    # tags pop as they ack, so a retry after a mid-batch
                    # failure never double-acks (AMQP closes the channel on
                    # an unknown delivery tag)
                    fault_point("connector.commit", connector="rabbitmq", epoch=_ep)
                    while _tags:
                        client.ack(_tags[0])
                        _tags.pop(0)

                try:
                    retry_call(_ack_remaining, policy=RetryPolicy(max_attempts=4),
                               description=f"rabbitmq ack epoch {ep}")
                except Exception as e:  # noqa: BLE001 - transient exhaustion
                    # keep the leftovers staged: a later commit retries them,
                    # and a crash redelivers them (redelivery > data loss)
                    if tags:
                        tags_by_epoch[ep] = tags
                    if isinstance(e, InjectedFault) and not e.transient:
                        raise  # InjectedCrash: worker-fatal, the task must die

        def await_commit(epoch: int, deadline_s: float = 30.0) -> None:
            """Checkpoint-then-stop: wait for the stopping epoch's commit so
            its tags ack before the connection closes (mirrors the committing
            operator wait in the task run loop)."""
            deadline = _time.monotonic() + deadline_s
            while _time.monotonic() < deadline:
                msg = sctx.poll_control()
                if msg is None:
                    _time.sleep(0.05)
                    continue
                if msg.kind == "stop":
                    # engine abort: the commit will never come — leave the
                    # tags unacked (broker redelivers) and shut down now
                    return
                if msg.kind == "commit" and msg.epoch is not None:
                    ack_through(msg.epoch)
                    if msg.epoch >= epoch:
                        return

        while True:
            if client.heartbeat and _time.monotonic() - last_sent > ka_interval:
                try:
                    client.send_heartbeat()
                except OSError:
                    flush()
                    return SourceFinishType.GRACEFUL
                last_sent = _time.monotonic()
            msg = sctx.poll_control()
            if msg is not None:
                if msg.kind == "checkpoint":
                    flush()
                    # the barrier only STAGES the tags under this epoch; the
                    # broker sees acks when the commit confirms durability
                    if pending_tags:
                        tags_by_epoch.setdefault(
                            msg.barrier.epoch, []).extend(pending_tags)
                        pending_tags = []
                    sctx.start_checkpoint(msg.barrier)
                    if msg.barrier.then_stop:
                        await_commit(msg.barrier.epoch)
                        client.close()
                        return SourceFinishType.FINAL
                elif msg.kind == "commit" and msg.epoch is not None:
                    ack_through(msg.epoch)
                elif msg.kind == "stop":
                    client.close()
                    return SourceFinishType.IMMEDIATE
            try:
                fault_point("connector.poll", connector="rabbitmq", key=self.queue)
                got = client.next_delivery()
                poll_backoff.reset()
            except InjectedFault as e:
                if not e.transient:
                    raise  # InjectedCrash: worker-fatal, the task must die
                _time.sleep(poll_backoff.next_delay())  # transient: retry
                continue
            except (TimeoutError, _socket.timeout):
                if de.should_flush():
                    flush()
                continue
            except ConnectionError:
                flush()
                return SourceFinishType.GRACEFUL
            if got is None:
                continue
            tag, body = got
            pending_tags.append(tag)
            de.deserialize(body, timestamp_micros=int(_time.time() * 1e6))
            if de.should_flush():
                flush()


@register_sink("rabbitmq")
class RabbitmqSink(Operator):
    """config: host, port, exchange ('' = default exchange), routing_key
    (defaults to queue, then ''), queue (declared when using the default
    exchange so publishes land somewhere), format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.exchange = str(cfg.get("exchange", ""))
        self.routing_key = str(cfg.get("routing_key", cfg.get("queue", "")))
        self.client: Optional[AmqpClient] = None

    def on_start(self, ctx):
        # write-mostly connection: disable heartbeats so a quiet input
        # stream cannot get the sink's connection reaped mid-job
        self.client = _client_from(self.cfg, heartbeat=0)
        if not self.exchange and self.cfg.get("queue"):
            # default-exchange publishes route by queue name; make sure the
            # queue exists (reference declares the same way)
            self.client.queue_declare(str(self.cfg["queue"]))

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..formats.registry import serialize_batch

        if self.client is None:
            self.on_start(ctx)
        for payload in serialize_batch(self.cfg, batch, self.cfg.get("schema")):
            self.client.publish(self.exchange, self.routing_key, payload)

    def on_close(self, ctx, collector):
        if self.client is not None:
            self.client.close()
