"""HTTP-family connectors: SSE source, polling source, webhook sink.

Reference: crates/arroyo-connectors/src/{sse,polling_http,webhook} — all
stdlib-implementable (http.client / urllib), no gating needed.
"""

from __future__ import annotations

import time
import urllib.request
from collections import deque
from typing import Optional
from urllib.parse import urlparse

from ..batch import Schema
from ..config import config
from ..operators.base import Operator, SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_sink, register_source


def _parse_headers(cfg: dict) -> dict[str, str]:
    out = {}
    raw = cfg.get("headers")
    if isinstance(raw, dict):
        return {str(k): str(v) for k, v in raw.items()}
    if raw:
        for part in str(raw).split(","):
            if ":" in part:
                k, v = part.split(":", 1)
                out[k.strip()] = v.strip()
    return out


class SSESource(SourceOperator):
    """Server-sent events (reference sse connector, eventsource protocol).
    config: endpoint, events (comma-separated filter), headers, schema +
    format options. State: Last-Event-ID for resumption."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.endpoint = str(cfg["endpoint"])
        self.event_filter = {
            e.strip() for e in str(cfg.get("events", "")).split(",") if e.strip()
        } or None
        self.headers = _parse_headers(cfg)

    def tables(self):
        return [TableSpec("e", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        import http.client

        from ..formats.registry import make_deserializer

        ctx = sctx.ctx
        if ctx.task_info.subtask_index != 0:
            return SourceFinishType.GRACEFUL
        tbl = ctx.table_manager.global_keyed("e")
        last_id = tbl.get("last_event_id")
        url = urlparse(self.endpoint)
        conn_cls = http.client.HTTPSConnection if url.scheme == "https" else http.client.HTTPConnection
        conn = conn_cls(url.netloc, timeout=10)
        headers = {"Accept": "text/event-stream", **self.headers}
        if last_id:
            headers["Last-Event-ID"] = last_id
        path = url.path + (f"?{url.query}" if url.query else "")
        conn.request("GET", path or "/", headers=headers)
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"SSE endpoint returned {resp.status}")
        de = make_deserializer(self.cfg, self.schema, task_info=ctx.task_info)
        # short socket timeout so control messages are polled between reads
        # (close-delimited responses detach conn.sock -> reach it via resp.fp)
        sock = conn.sock if conn.sock is not None else resp.fp.raw._sock
        sock.settimeout(0.2)

        # own line accumulator over resp.read1 (which applies chunked
        # transfer decoding, unlike reading resp.fp directly) so a timeout
        # mid-line never discards the partial line
        acc = bytearray()
        lines: deque[bytes] = deque()
        stream_done = False
        data_lines: list[str] = []
        event_type = "message"
        while True:
            msg = sctx.poll_control()
            if msg is not None:
                if msg.kind == "checkpoint":
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    if last_id is not None:
                        tbl.insert("last_event_id", last_id)
                    sctx.start_checkpoint(msg.barrier)
                    if msg.barrier.then_stop:
                        return SourceFinishType.FINAL
                elif msg.kind == "stop":
                    return SourceFinishType.IMMEDIATE
            if not lines:
                if stream_done:
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    return SourceFinishType.GRACEFUL
                try:
                    chunk = resp.read1(65536)
                except (TimeoutError, OSError):
                    if de.should_flush():
                        b = de.flush()
                        if b is not None:
                            collector.collect(b)
                    continue
                if not chunk:
                    stream_done = True
                    if acc:
                        lines.append(bytes(acc))
                        acc.clear()
                    continue
                acc += chunk
                while True:
                    nl = acc.find(b"\n")
                    if nl < 0:
                        break
                    lines.append(bytes(acc[:nl]))
                    del acc[: nl + 1]
                continue
            raw = lines.popleft()
            line = raw.decode("utf-8").rstrip("\r")
            if not line:  # dispatch event
                if data_lines and (self.event_filter is None or event_type in self.event_filter):
                    de.deserialize(
                        "\n".join(data_lines),
                        timestamp_micros=int(time.time() * 1e6),
                    )
                    if de.should_flush():
                        b = de.flush()
                        if b is not None:
                            collector.collect(b)
                data_lines = []
                event_type = "message"
                continue
            if line.startswith(":"):
                continue
            field, _, value = line.partition(":")
            value = value.lstrip(" ")
            if field == "data":
                data_lines.append(value)
            elif field == "event":
                event_type = value
            elif field == "id":
                last_id = value


class PollingHTTPSource(SourceOperator):
    """config: endpoint, poll_interval_ms (default 1000), emit_behavior:
    'all' | 'changed' (dedupe identical bodies), method, body, headers,
    framing, schema + format options (reference polling_http connector)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.endpoint = str(cfg["endpoint"])
        self.interval_s = int(cfg.get("poll_interval_ms", 1000)) / 1000
        self.emit_behavior = str(cfg.get("emit_behavior", "all"))
        self.method = str(cfg.get("method", "GET"))
        self.body = cfg.get("body")
        self.headers = _parse_headers(cfg)
        self.max_polls = cfg.get("testing.max_polls")  # deterministic tests

    # no state tables: this source is non-replayable (no seekable
    # offset), so there is nothing to snapshot — LR203 rejects a
    # declared-but-unwired TableSpec

    def run(self, sctx, collector) -> SourceFinishType:
        from ..formats.framing import frame_iter
        from ..formats.registry import default_framing, make_deserializer

        ctx = sctx.ctx
        if ctx.task_info.subtask_index != 0:
            return SourceFinishType.GRACEFUL
        de = make_deserializer(self.cfg, self.schema, task_info=ctx.task_info)
        framing = default_framing(self.cfg) or "newline"
        last_body: Optional[bytes] = None
        polls = 0
        next_poll = time.monotonic()
        while True:
            msg = sctx.poll_control()
            if msg is not None:
                if msg.kind == "checkpoint":
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    sctx.start_checkpoint(msg.barrier)
                    if msg.barrier.then_stop:
                        return SourceFinishType.FINAL
                elif msg.kind == "stop":
                    return SourceFinishType.IMMEDIATE
            now = time.monotonic()
            if now < next_poll:
                time.sleep(min(next_poll - now, 0.05))
                continue
            next_poll = now + self.interval_s
            req = urllib.request.Request(
                self.endpoint, method=self.method,
                data=self.body.encode() if self.body else None,
                headers=self.headers,
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    body = resp.read()
            except Exception as exc:
                # transport errors go through the SAME bad_data policy as
                # decode errors — counted and surfaced, never silently eaten
                if de.drop_bad_data(exc):
                    continue
                raise
            if self.emit_behavior == "changed" and body == last_body:
                continue
            last_body = body
            ts = int(time.time() * 1e6)
            for frame in frame_iter(body, framing):
                de.deserialize(frame, timestamp_micros=ts)
            b = de.flush()
            if b is not None:
                collector.collect(b)
            polls += 1
            if self.max_polls is not None and polls >= int(self.max_polls):
                return SourceFinishType.GRACEFUL


class WebhookSink(Operator):
    """config: endpoint, headers, format options — POSTs each serialized
    message (reference webhook connector)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.endpoint = str(cfg["endpoint"])
        self.headers = _parse_headers(cfg)
        self.schema = cfg.get("schema")

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..formats.registry import serialize_batch

        for payload in serialize_batch(self.cfg, batch, self.schema):
            req = urllib.request.Request(
                self.endpoint, data=payload, method="POST",
                headers={"Content-Type": "application/json", **self.headers},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()


register_source("sse")(SSESource)
register_source("polling_http")(PollingHTTPSource)
register_sink("webhook")(WebhookSink)
