"""stdout sink (reference crates/arroyo-connectors stdout)."""

from __future__ import annotations

import sys

from ..formats.json_fmt import serialize_json_lines
from ..operators.base import Operator
from . import register_sink


class StdoutSink(Operator):
    def __init__(self, cfg: dict):
        pass

    def process_batch(self, batch, ctx, collector, input_index=0):
        for line in serialize_json_lines(batch):
            sys.stdout.write(line + "\n")
        sys.stdout.flush()


register_sink("stdout")(StdoutSink)
