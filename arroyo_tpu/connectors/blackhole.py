"""blackhole sink: discards everything (reference arroyo-connectors
blackhole; used as the benchmark sink)."""

from __future__ import annotations

from ..operators.base import Operator
from . import register_sink


class BlackholeSink(Operator):
    def __init__(self, cfg: dict):
        self.rows_seen = 0  # state: ephemeral — debug/test counter on a throwaway sink; not part of any output contract

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.rows_seen += batch.num_rows


register_sink("blackhole")(BlackholeSink)
