"""WebSocket source with a from-scratch RFC 6455 client.

Reference: crates/arroyo-connectors/src/websocket (tungstenite client with
optional subscription messages). Implemented over raw sockets — handshake,
frame codec, client masking — so it needs no external package.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import time
from typing import Iterator, Optional
from urllib.parse import urlparse

from ..batch import Schema
from ..operators.base import SourceOperator
from ..types import SourceFinishType
from . import register_source

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def encode_frame(opcode: int, payload: bytes, mask: bool) -> bytes:
    """One FIN frame (fragmentation is not produced, only consumed)."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < (1 << 16):
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


class FrameReader:
    """Incremental frame decoder (server->client frames are unmasked; a
    masked frame from a misbehaving peer is still unmasked correctly)."""

    def __init__(self):
        self.buf = b""
        self._fragments: list[bytes] = []
        self._frag_opcode: Optional[int] = None

    def feed(self, data: bytes) -> Iterator[tuple[int, bytes]]:
        self.buf += data
        while True:
            frame = self._try_parse()
            if frame is None:
                return
            fin, opcode, payload = frame
            if opcode == 0x0:  # continuation
                self._fragments.append(payload)
                if fin and self._frag_opcode is not None:
                    yield self._frag_opcode, b"".join(self._fragments)
                    self._fragments, self._frag_opcode = [], None
            elif not fin:
                self._fragments = [payload]
                self._frag_opcode = opcode
            else:
                yield opcode, payload

    def _try_parse(self):
        buf = self.buf
        if len(buf) < 2:
            return None
        fin = bool(buf[0] & 0x80)
        opcode = buf[0] & 0x0F
        masked = bool(buf[1] & 0x80)
        n = buf[1] & 0x7F
        off = 2
        if n == 126:
            if len(buf) < 4:
                return None
            n = struct.unpack(">H", buf[2:4])[0]
            off = 4
        elif n == 127:
            if len(buf) < 10:
                return None
            n = struct.unpack(">Q", buf[2:10])[0]
            off = 10
        key = None
        if masked:
            if len(buf) < off + 4:
                return None
            key = buf[off : off + 4]
            off += 4
        if len(buf) < off + n:
            return None
        payload = buf[off : off + n]
        if key:
            payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        self.buf = buf[off + n :]
        return fin, opcode, payload


def client_handshake(sock: socket.socket, host: str, path: str,
                     headers: Optional[dict] = None) -> bytes:
    """Performs the upgrade; returns any frame bytes that arrived with the
    handshake response."""
    key = base64.b64encode(os.urandom(16)).decode()
    lines = [
        f"GET {path or '/'} HTTP/1.1",
        f"Host: {host}",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Key: {key}",
        "Sec-WebSocket-Version: 13",
    ]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
    resp = b""
    while b"\r\n\r\n" not in resp:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("websocket handshake: connection closed")
        resp += chunk
    head, rest = resp.split(b"\r\n\r\n", 1)
    status = head.split(b"\r\n", 1)[0]
    if b"101" not in status:
        raise ConnectionError(f"websocket handshake rejected: {status.decode()}")
    expect = base64.b64encode(
        hashlib.sha1((key + _WS_GUID).encode()).digest()
    ).decode()
    for line in head.decode().split("\r\n")[1:]:
        k, _, v = line.partition(":")
        if k.strip().lower() == "sec-websocket-accept" and v.strip() != expect:
            raise ConnectionError("websocket handshake: bad accept key")
    # any bytes after the handshake are already frames
    return rest


def accept_handshake(conn: socket.socket) -> None:
    """Server side of the handshake (used by tests and the webhook-style
    receiving end)."""
    req = b""
    while b"\r\n\r\n" not in req:
        chunk = conn.recv(4096)
        if not chunk:
            raise ConnectionError("closed during handshake")
        req += chunk
    key = ""
    for line in req.decode(errors="replace").split("\r\n"):
        k, _, v = line.partition(":")
        if k.strip().lower() == "sec-websocket-key":
            key = v.strip()
    accept = base64.b64encode(hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()
    conn.sendall(
        (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept}\r\n\r\n"
        ).encode()
    )


class WebSocketSource(SourceOperator):
    """config: endpoint (ws://host:port/path), subscription_message
    (sent once after connect), headers, schema + format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.endpoint = str(cfg["endpoint"])
        self.subscription = cfg.get("subscription_message")

    # no state tables: this source is non-replayable (no seekable
    # offset), so there is nothing to snapshot — LR203 rejects a
    # declared-but-unwired TableSpec

    def run(self, sctx, collector) -> SourceFinishType:
        from ..formats.registry import make_deserializer

        ctx = sctx.ctx
        if ctx.task_info.subtask_index != 0:
            return SourceFinishType.GRACEFUL
        url = urlparse(self.endpoint)
        if url.scheme not in ("ws", "wss"):
            raise ValueError(f"websocket endpoint must be ws:// or wss://, got {self.endpoint}")
        port = url.port or (443 if url.scheme == "wss" else 80)
        sock = socket.create_connection((url.hostname, port), timeout=10)
        if url.scheme == "wss":
            import ssl

            sock = ssl.create_default_context().wrap_socket(
                sock, server_hostname=url.hostname
            )
        path = url.path + (f"?{url.query}" if url.query else "")
        from .http_conn import _parse_headers

        leftover = client_handshake(sock, url.netloc, path, _parse_headers(self.cfg))
        reader = FrameReader()
        pending = list(reader.feed(leftover)) if leftover else []
        if self.subscription:
            sock.sendall(encode_frame(OP_TEXT, str(self.subscription).encode(), mask=True))
        sock.settimeout(0.2)
        de = make_deserializer(self.cfg, self.schema, task_info=ctx.task_info)
        while True:
            msg = sctx.poll_control()
            if msg is not None:
                if msg.kind == "checkpoint":
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    sctx.start_checkpoint(msg.barrier)
                    if msg.barrier.then_stop:
                        sock.close()
                        return SourceFinishType.FINAL
                elif msg.kind == "stop":
                    sock.close()
                    return SourceFinishType.IMMEDIATE
            frames = pending
            pending = []
            if not frames:
                try:
                    data = sock.recv(65536)
                except (TimeoutError, socket.timeout):
                    if de.should_flush():
                        b = de.flush()
                        if b is not None:
                            collector.collect(b)
                    continue
                if not data:
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    return SourceFinishType.GRACEFUL
                frames = list(reader.feed(data))
            for opcode, payload in frames:
                if opcode == OP_PING:
                    sock.sendall(encode_frame(OP_PONG, payload, mask=True))
                elif opcode == OP_CLOSE:
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    sock.close()
                    return SourceFinishType.GRACEFUL
                elif opcode in (OP_TEXT, OP_BINARY):
                    de.deserialize(payload, timestamp_micros=int(time.time() * 1e6))
                    if de.should_flush():
                        b = de.flush()
                        if b is not None:
                            collector.collect(b)


register_source("websocket")(WebSocketSource)
