"""Gated connectors: broker integrations that need client libraries not in
the air-gapped image (reference arroyo-connectors §2.9). mqtt, nats,
rabbitmq, and kinesis have from-scratch protocol implementations (their own
modules); fluvio's wire protocol is a moving custom binary format with no
stable public spec, so it registers here with its config surface documented,
and constructing one without its client package raises with install
instructions, matching how the kafka connector degrades.
"""

from __future__ import annotations

from . import register_sink, register_source

_SPECS = {
    "fluvio": {
        "package": "fluvio",
        "options": ["endpoint", "topic"],
        "kinds": ("source", "sink"),
    },
}


def _make_stub(name: str, spec: dict):
    class _Stub:
        def __init__(self, cfg: dict):
            raise ImportError(
                f"the {name!r} connector requires the {spec['package']!r} "
                f"package, which is not installed in this image. "
                f"Options: {', '.join(spec['options'])}. "
                f"pip install {spec['package']} to enable it."
            )

    _Stub.__name__ = f"{name.capitalize()}Connector"
    return _Stub


for _name, _spec in _SPECS.items():
    stub = _make_stub(_name, _spec)
    if "source" in _spec["kinds"]:
        register_source(_name)(stub)
    if "sink" in _spec["kinds"]:
        register_sink(_name)(stub)
