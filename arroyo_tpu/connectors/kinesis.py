"""Kinesis connector: source + sink over a from-scratch HTTP/JSON client.

Reference: crates/arroyo-connectors/src/kinesis (rusoto-based shard reader
with per-shard iterators + PutRecords sink). Kinesis Data Streams speaks
plain HTTP with ``X-Amz-Target: Kinesis_20131202.<Op>`` JSON bodies and
SigV4 request signing — both implemented here directly (hashlib/hmac), no
boto3, keeping the connector dependency-free for the air-gapped image
(same approach as the NATS/MQTT/redis connectors).

Options: stream_name, aws_region (default us-east-1), endpoint (override
for tests/localstack), aws_access_key_id / aws_secret_access_key (or the
standard env vars), 'source.offset' = earliest|latest (shard TRIM_HORIZON
vs LATEST). The source checkpoints the last-read sequence number per shard
and resumes AFTER_SEQUENCE_NUMBER; shards split across subtasks by index.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import os
import time
import urllib.error
import urllib.request
from typing import Optional

from ..batch import Schema
from ..operators.base import Operator, SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_sink, register_source


class KinesisError(RuntimeError):
    pass


class KinesisClient:
    """Minimal Kinesis Data Streams client: signed JSON POSTs."""

    def __init__(self, region: str = "us-east-1", endpoint: Optional[str] = None,
                 access_key: Optional[str] = None, secret_key: Optional[str] = None,
                 timeout: float = 10.0):
        self.region = region
        self.endpoint = (endpoint or f"https://kinesis.{region}.amazonaws.com").rstrip("/")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "anonymous")
        self.secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "anonymous")
        self.timeout = timeout
        self.host = self.endpoint.split("://", 1)[1].split("/", 1)[0]

    # ------------------------------------------------------------- signing

    def _sign(self, body: bytes, target: str, amz_date: str) -> str:
        """AWS Signature Version 4 for a kinesis POST /."""
        date_stamp = amz_date[:8]
        payload_hash = hashlib.sha256(body).hexdigest()
        canonical_headers = (
            f"content-type:application/x-amz-json-1.1\nhost:{self.host}\n"
            f"x-amz-date:{amz_date}\nx-amz-target:{target}\n")
        signed_headers = "content-type;host;x-amz-date;x-amz-target"
        canonical_request = (
            f"POST\n/\n\n{canonical_headers}\n{signed_headers}\n{payload_hash}")
        scope = f"{date_stamp}/{self.region}/kinesis/aws4_request"
        string_to_sign = (
            f"AWS4-HMAC-SHA256\n{amz_date}\n{scope}\n"
            + hashlib.sha256(canonical_request.encode()).hexdigest())

        def hm(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hm(b"AWS4" + self.secret_key.encode(), date_stamp)
        k = hm(k, self.region)
        k = hm(k, "kinesis")
        k = hm(k, "aws4_request")
        sig = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
        return (f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders={signed_headers}, Signature={sig}")

    def call(self, op: str, payload: dict) -> dict:
        target = f"Kinesis_20131202.{op}"
        body = json.dumps(payload).encode()
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
        req = urllib.request.Request(
            self.endpoint + "/", data=body, method="POST",
            headers={
                "Content-Type": "application/x-amz-json-1.1",
                "X-Amz-Target": target,
                "X-Amz-Date": amz_date,
                "Authorization": self._sign(body, target, amz_date),
            })
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise KinesisError(f"{op} failed: HTTP {e.code}: {detail}") from e
        except urllib.error.URLError as e:
            raise KinesisError(f"{op} failed: {e.reason}") from e

    # ------------------------------------------------------------ wrappers

    def list_shards(self, stream: str) -> list[str]:
        out: list[str] = []
        token: Optional[str] = None
        while True:
            payload: dict = ({"NextToken": token} if token
                             else {"StreamName": stream})
            resp = self.call("ListShards", payload)
            out.extend(s["ShardId"] for s in resp.get("Shards", []))
            token = resp.get("NextToken")
            if not token:
                return out

    def shard_iterator(self, stream: str, shard: str, kind: str,
                       sequence: Optional[str] = None) -> str:
        payload = {"StreamName": stream, "ShardId": shard,
                   "ShardIteratorType": kind}
        if sequence is not None:
            payload["StartingSequenceNumber"] = sequence
        return self.call("GetShardIterator", payload)["ShardIterator"]

    def get_records(self, iterator: str, limit: int = 1000) -> dict:
        return self.call("GetRecords", {"ShardIterator": iterator, "Limit": limit})

    def put_records(self, stream: str, records: list[tuple[bytes, str]],
                    max_retries: int = 8) -> None:
        """Retries ONLY the failed subset on partial failure (per-record
        throttling is routine under load; re-sending the whole batch would
        duplicate the records that already landed)."""
        pending = records
        for attempt in range(max_retries + 1):
            resp = self.call("PutRecords", {
                "StreamName": stream,
                "Records": [
                    {"Data": base64.b64encode(data).decode(), "PartitionKey": pk}
                    for data, pk in pending
                ],
            })
            if not int(resp.get("FailedRecordCount", 0)):
                return
            results = resp.get("Records", [])
            pending = [rec for rec, res in zip(pending, results)
                       if res.get("ErrorCode")]
            if not pending:
                return
            time.sleep(min(0.1 * 2 ** attempt, 2.0))
        raise KinesisError(
            f"PutRecords: {len(pending)} records still failing after "
            f"{max_retries} retries")


def _client_from(cfg: dict) -> KinesisClient:
    return KinesisClient(
        region=str(cfg.get("aws_region", "us-east-1")),
        endpoint=cfg.get("endpoint"),
        access_key=cfg.get("aws_access_key_id"),
        secret_key=cfg.get("aws_secret_access_key"),
    )


@register_source("kinesis")
class KinesisSource(SourceOperator):
    """config: stream_name, aws_region, endpoint, 'source.offset',
    schema + format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.stream = str(cfg["stream_name"])
        self.offset = str(cfg.get("source.offset", "earliest"))

    def tables(self):
        return [TableSpec("k", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        from ..formats.registry import make_deserializer

        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        par = ctx.task_info.parallelism
        tbl = ctx.table_manager.global_keyed("k")
        # union sequence numbers from every prior subtask: shards can move
        # between subtasks after a rescale (same rule as the kafka source)
        seqs: dict[str, str] = {}
        for _old_sub, saved in tbl.items():
            if saved:
                seqs.update(saved)
        client = _client_from(self.cfg)
        kind = "TRIM_HORIZON" if self.offset == "earliest" else "LATEST"
        iters: dict[str, Optional[str]] = {}
        mine: list[str] = []

        def assign_shards() -> None:
            """(Re)list shards and open iterators for newly-seen ones —
            called at start and after a reshard closes this subtask's
            shards (parents close, children appear)."""
            shards = sorted(client.list_shards(self.stream))
            mine[:] = [s for i, s in enumerate(shards) if i % par == sub]
            for s in mine:
                if s in iters:
                    continue
                if s in seqs:
                    iters[s] = client.shard_iterator(
                        self.stream, s, "AFTER_SEQUENCE_NUMBER", seqs[s])
                else:
                    iters[s] = client.shard_iterator(self.stream, s, kind)

        assign_shards()
        de = make_deserializer(self.cfg, self.schema)

        def flush():
            b = de.flush()
            if b is not None:
                collector.collect(b)

        idle_sleep = float(self.cfg.get("poll_interval_s", 0.2))
        # AWS caps GetRecords at 5 calls/sec/shard: pace each shard
        min_gap = float(self.cfg.get("shard_poll_gap_s", 0.2))
        last_poll: dict[str, float] = {}
        backoff = 0.0
        reshard_check = time.monotonic()
        while True:
            msg = sctx.poll_control()
            if msg is not None:
                if msg.kind == "checkpoint":
                    flush()
                    tbl.insert(sub, dict(seqs))
                    sctx.start_checkpoint(msg.barrier)
                    if msg.barrier.then_stop:
                        return SourceFinishType.FINAL
                elif msg.kind == "stop":
                    return SourceFinishType.IMMEDIATE
            got_any = False
            for s in list(mine):
                it = iters.get(s)
                if it is None:
                    continue  # shard closed (reshard); children picked up below
                now = time.monotonic()
                if now - last_poll.get(s, 0.0) < min_gap:
                    continue
                last_poll[s] = now
                try:
                    resp = client.get_records(it)
                    backoff = 0.0
                except KinesisError:
                    # throttling / transient failure: back off and refresh
                    # the iterator (a >5min outage expires it — retrying the
                    # stale one would wedge the shard forever); never kill
                    # the task over a routine 400
                    backoff = min(max(backoff * 2, 0.2), 5.0)
                    time.sleep(backoff)
                    try:
                        if s in seqs:
                            iters[s] = client.shard_iterator(
                                self.stream, s, "AFTER_SEQUENCE_NUMBER", seqs[s])
                        else:
                            iters[s] = client.shard_iterator(self.stream, s, kind)
                    except KinesisError:
                        pass  # next sweep retries with the old iterator
                    continue
                iters[s] = resp.get("NextShardIterator")
                for rec in resp.get("Records", []):
                    got_any = True
                    data = base64.b64decode(rec["Data"])
                    seqs[s] = rec["SequenceNumber"]
                    ts = rec.get("ApproximateArrivalTimestamp")
                    ts_us = int(float(ts) * 1e6) if ts else int(time.time() * 1e6)
                    de.deserialize(data, timestamp_micros=ts_us)
                    if de.should_flush():
                        flush()
            all_closed = bool(mine) and all(iters.get(s) is None for s in mine)
            if (all_closed or not mine) and time.monotonic() - reshard_check > 2.0:
                # a reshard closes parents and creates children; a subtask
                # with no shards (parallelism > shard count) may gain some
                reshard_check = time.monotonic()
                try:
                    assign_shards()
                except KinesisError:
                    pass
            if not got_any:
                if de.should_flush():
                    flush()
                time.sleep(idle_sleep)


@register_sink("kinesis")
class KinesisSink(Operator):
    """config: stream_name, aws_region, endpoint, format options. Rows are
    partitioned by the batch's routing key when present (stable shard
    placement), else round-robin."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.stream = str(cfg["stream_name"])
        self.client: Optional[KinesisClient] = None
        self._rr = 0

    def on_start(self, ctx):
        self.client = _client_from(self.cfg)

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..batch import KEY_FIELD
        from ..formats.registry import serialize_batch

        if self.client is None:
            self.on_start(ctx)
        payloads = serialize_batch(self.cfg, batch, self.cfg.get("schema"))
        if KEY_FIELD in batch.columns:
            pks = [str(int(k)) for k in batch.keys]
        else:
            pks = []
            for _ in payloads:
                self._rr += 1
                pks.append(str(self._rr))
        records = list(zip(payloads, pks))
        # PutRecords caps at 500 records per request
        for i in range(0, len(records), 500):
            self.client.put_records(self.stream, records[i:i + 500])
