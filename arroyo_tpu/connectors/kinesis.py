"""Kinesis connector: source + sink over a from-scratch HTTP/JSON client.

Reference: crates/arroyo-connectors/src/kinesis (rusoto-based shard reader
with per-shard iterators + PutRecords sink). Kinesis Data Streams speaks
plain HTTP with ``X-Amz-Target: Kinesis_20131202.<Op>`` JSON bodies and
SigV4 request signing — both implemented here directly (hashlib/hmac), no
boto3, keeping the connector dependency-free for the air-gapped image
(same approach as the NATS/MQTT/redis connectors).

Options: stream_name, aws_region (default us-east-1), endpoint (override
for tests/localstack), aws_access_key_id / aws_secret_access_key (or the
standard env vars), 'source.offset' = earliest|latest (shard TRIM_HORIZON
vs LATEST). The source checkpoints the last-read sequence number per shard
and resumes AFTER_SEQUENCE_NUMBER.

Shard -> subtask assignment is a STABLE hash of the shard id
(crc32(shard_id) % parallelism, identical on every worker), and every
subtask re-lists shards periodically regardless of its open-shard state:
after a reshard, child shards are picked up by whichever subtask owns them
and an existing open shard can never migrate or double-assign when the
shard list changes (index-mod assignment shifted every surviving shard on
each reshard, silently dropping children and transiently double-reading).
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import os
import time
import urllib.error
import urllib.request
import zlib
from typing import Optional

from ..batch import Schema
from ..faults import InjectedFault, fault_point
from ..operators.base import Operator, SourceOperator, TableSpec
from ..types import SourceFinishType
from ..utils.retry import Backoff, RetryPolicy
from . import register_sink, register_source


def shard_owner(shard_id: str, parallelism: int) -> int:
    """Stable shard->subtask assignment: identical across processes and
    restarts (python's hash() is salted per process, so it cannot be used)."""
    return zlib.crc32(shard_id.encode()) % max(parallelism, 1)


class KinesisError(RuntimeError):
    pass


class KinesisClient:
    """Minimal Kinesis Data Streams client: signed JSON POSTs."""

    def __init__(self, region: str = "us-east-1", endpoint: Optional[str] = None,
                 access_key: Optional[str] = None, secret_key: Optional[str] = None,
                 timeout: float = 10.0):
        self.region = region
        self.endpoint = (endpoint or f"https://kinesis.{region}.amazonaws.com").rstrip("/")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "anonymous")
        self.secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "anonymous")
        self.timeout = timeout
        self.host = self.endpoint.split("://", 1)[1].split("/", 1)[0]

    # ------------------------------------------------------------- signing

    def _sign(self, body: bytes, target: str, amz_date: str) -> str:
        """AWS Signature Version 4 for a kinesis POST /."""
        date_stamp = amz_date[:8]
        payload_hash = hashlib.sha256(body).hexdigest()
        canonical_headers = (
            f"content-type:application/x-amz-json-1.1\nhost:{self.host}\n"
            f"x-amz-date:{amz_date}\nx-amz-target:{target}\n")
        signed_headers = "content-type;host;x-amz-date;x-amz-target"
        canonical_request = (
            f"POST\n/\n\n{canonical_headers}\n{signed_headers}\n{payload_hash}")
        scope = f"{date_stamp}/{self.region}/kinesis/aws4_request"
        string_to_sign = (
            f"AWS4-HMAC-SHA256\n{amz_date}\n{scope}\n"
            + hashlib.sha256(canonical_request.encode()).hexdigest())

        def hm(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hm(b"AWS4" + self.secret_key.encode(), date_stamp)
        k = hm(k, self.region)
        k = hm(k, "kinesis")
        k = hm(k, "aws4_request")
        sig = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
        return (f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders={signed_headers}, Signature={sig}")

    def call(self, op: str, payload: dict) -> dict:
        target = f"Kinesis_20131202.{op}"
        body = json.dumps(payload).encode()
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
        req = urllib.request.Request(
            self.endpoint + "/", data=body, method="POST",
            headers={
                "Content-Type": "application/x-amz-json-1.1",
                "X-Amz-Target": target,
                "X-Amz-Date": amz_date,
                "Authorization": self._sign(body, target, amz_date),
            })
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise KinesisError(f"{op} failed: HTTP {e.code}: {detail}") from e
        except urllib.error.URLError as e:
            raise KinesisError(f"{op} failed: {e.reason}") from e

    # ------------------------------------------------------------ wrappers

    def list_shards(self, stream: str) -> list[str]:
        out: list[str] = []
        token: Optional[str] = None
        while True:
            payload: dict = ({"NextToken": token} if token
                             else {"StreamName": stream})
            resp = self.call("ListShards", payload)
            out.extend(s["ShardId"] for s in resp.get("Shards", []))
            token = resp.get("NextToken")
            if not token:
                return out

    def shard_iterator(self, stream: str, shard: str, kind: str,
                       sequence: Optional[str] = None) -> str:
        payload = {"StreamName": stream, "ShardId": shard,
                   "ShardIteratorType": kind}
        if sequence is not None:
            payload["StartingSequenceNumber"] = sequence
        return self.call("GetShardIterator", payload)["ShardIterator"]

    def get_records(self, iterator: str, limit: int = 1000) -> dict:
        return self.call("GetRecords", {"ShardIterator": iterator, "Limit": limit})

    def put_records(self, stream: str, records: list[tuple[bytes, str]],
                    max_retries: int = 8) -> None:
        """Retries ONLY the failed subset on partial failure (per-record
        throttling is routine under load; re-sending the whole batch would
        duplicate the records that already landed). Delays come from the
        shared backoff layer so chaos runs and production behave alike."""
        pending = records
        backoff = Backoff(RetryPolicy(max_attempts=max_retries,
                                      base_delay_s=0.1, max_delay_s=2.0,
                                      jitter=0.2))
        while True:
            resp = self.call("PutRecords", {
                "StreamName": stream,
                "Records": [
                    {"Data": base64.b64encode(data).decode(), "PartitionKey": pk}
                    for data, pk in pending
                ],
            })
            if not int(resp.get("FailedRecordCount", 0)):
                return
            results = resp.get("Records", [])
            pending = [rec for rec, res in zip(pending, results)
                       if res.get("ErrorCode")]
            if not pending:
                return
            if backoff.exhausted():
                raise KinesisError(
                    f"PutRecords: {len(pending)} records still failing after "
                    f"{max_retries} retries")
            time.sleep(backoff.next_delay())


def _client_from(cfg: dict) -> KinesisClient:
    return KinesisClient(
        region=str(cfg.get("aws_region", "us-east-1")),
        endpoint=cfg.get("endpoint"),
        access_key=cfg.get("aws_access_key_id"),
        secret_key=cfg.get("aws_secret_access_key"),
    )


@register_source("kinesis")
class KinesisSource(SourceOperator):
    """config: stream_name, aws_region, endpoint, 'source.offset',
    schema + format options."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.schema: Schema = cfg["schema"]
        self.stream = str(cfg["stream_name"])
        self.offset = str(cfg.get("source.offset", "earliest"))

    def tables(self):
        return [TableSpec("k", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        from ..formats.registry import make_deserializer

        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        par = ctx.task_info.parallelism
        tbl = ctx.table_manager.global_keyed("k")
        # union sequence numbers from every prior subtask: shards can move
        # between subtasks after a rescale (same rule as the kafka source)
        seqs: dict[str, str] = {}
        for _old_sub, saved in tbl.items():
            if saved:
                seqs.update(saved)
        client = _client_from(self.cfg)
        kind = "TRIM_HORIZON" if self.offset == "earliest" else "LATEST"
        iters: dict[str, Optional[str]] = {}
        mine: list[str] = []
        first_list = True

        def assign_shards() -> None:
            """(Re)list shards and open iterators for newly-owned ones.
            Ownership is the stable crc32 hash, so re-listing NEVER moves a
            shard between subtasks — child shards appear under their owner
            and open shards cannot double-assign during a reshard."""
            nonlocal first_list
            shards = client.list_shards(self.stream)
            mine[:] = sorted(s for s in shards if shard_owner(s, par) == sub)
            for s in mine:
                if s in iters:
                    continue
                if s in seqs:
                    iters[s] = client.shard_iterator(
                        self.stream, s, "AFTER_SEQUENCE_NUMBER", seqs[s])
                else:
                    # the configured LATEST/TRIM_HORIZON offset applies only
                    # to the startup listing; a shard appearing mid-run is a
                    # reshard child whose records must be read from the
                    # start or everything written before discovery is lost
                    iters[s] = client.shard_iterator(
                        self.stream, s, kind if first_list else "TRIM_HORIZON")
            first_list = False

        assign_shards()
        de = make_deserializer(self.cfg, self.schema, task_info=ctx.task_info)

        def flush():
            b = de.flush()
            if b is not None:
                collector.collect(b)

        idle_sleep = float(self.cfg.get("poll_interval_s", 0.2))
        # AWS caps GetRecords at 5 calls/sec/shard: pace each shard
        min_gap = float(self.cfg.get("shard_poll_gap_s", 0.2))
        # every subtask re-lists periodically even while its shards are
        # healthy: a reshard's children otherwise sit unread forever on any
        # subtask that still has open long-lived shards
        reshard_interval = float(self.cfg.get("reshard_interval_s", 5.0))
        last_poll: dict[str, float] = {}
        backoff = Backoff(RetryPolicy(max_attempts=1 << 30, base_delay_s=0.2,
                                      max_delay_s=5.0, jitter=0.25))
        reshard_check = time.monotonic()
        while True:
            msg = sctx.poll_control()
            if msg is not None:
                if msg.kind == "checkpoint":
                    flush()
                    tbl.insert(sub, dict(seqs))
                    sctx.start_checkpoint(msg.barrier)
                    if msg.barrier.then_stop:
                        return SourceFinishType.FINAL
                elif msg.kind == "stop":
                    return SourceFinishType.IMMEDIATE
            got_any = False
            for s in list(mine):
                it = iters.get(s)
                if it is None:
                    continue  # shard closed (reshard); children re-listed below
                now = time.monotonic()
                if now - last_poll.get(s, 0.0) < min_gap:
                    continue
                last_poll[s] = now
                try:
                    fault_point("connector.poll", connector="kinesis", key=s)
                    resp = client.get_records(it)
                    backoff.reset()
                except (KinesisError, InjectedFault) as e:
                    if isinstance(e, InjectedFault) and not e.transient:
                        raise  # InjectedCrash: worker-fatal, the task must die
                    # throttling / transient failure: back off (shared layer)
                    # and refresh the iterator (a >5min outage expires it —
                    # retrying the stale one would wedge the shard forever);
                    # never kill the task over a routine 400
                    time.sleep(backoff.next_delay())
                    try:
                        if s in seqs:
                            iters[s] = client.shard_iterator(
                                self.stream, s, "AFTER_SEQUENCE_NUMBER", seqs[s])
                        else:
                            iters[s] = client.shard_iterator(self.stream, s, kind)
                    except KinesisError:
                        pass  # next sweep retries with the old iterator
                    continue
                iters[s] = resp.get("NextShardIterator")
                for rec in resp.get("Records", []):
                    got_any = True
                    data = base64.b64decode(rec["Data"])
                    seqs[s] = rec["SequenceNumber"]
                    ts = rec.get("ApproximateArrivalTimestamp")
                    ts_us = int(float(ts) * 1e6) if ts else int(time.time() * 1e6)
                    de.deserialize(data, timestamp_micros=ts_us)
                    if de.should_flush():
                        flush()
            all_closed = bool(mine) and all(iters.get(s) is None for s in mine)
            now = time.monotonic()
            # a subtask with nothing open re-lists eagerly (2s); a healthy
            # one still sweeps every reshard_interval for child shards
            if (now - reshard_check
                    > (min(2.0, reshard_interval) if (all_closed or not mine)
                       else reshard_interval)):
                reshard_check = now
                try:
                    assign_shards()
                except KinesisError:
                    pass
            if not got_any:
                if de.should_flush():
                    flush()
                time.sleep(idle_sleep)


@register_sink("kinesis")
class KinesisSink(Operator):
    """config: stream_name, aws_region, endpoint, format options. Rows are
    partitioned by the batch's routing key when present (stable shard
    placement), else round-robin."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.stream = str(cfg["stream_name"])
        self.client: Optional[KinesisClient] = None
        self._rr = 0  # state: ephemeral — round-robin shard spreading for keyless rows; placement is not part of the replay contract (at-least-once sink)

    def on_start(self, ctx):
        self.client = _client_from(self.cfg)

    def process_batch(self, batch, ctx, collector, input_index=0):
        from ..batch import KEY_FIELD
        from ..formats.registry import serialize_batch

        if self.client is None:
            self.on_start(ctx)
        payloads = serialize_batch(self.cfg, batch, self.cfg.get("schema"))
        if KEY_FIELD in batch.columns:
            pks = [str(int(k)) for k in batch.keys]
        else:
            pks = []
            for _ in payloads:
                self._rr += 1
                pks.append(str(self._rr))
        records = list(zip(payloads, pks))
        # PutRecords caps at 500 records per request
        for i in range(0, len(records), 500):
            self.client.put_records(self.stream, records[i:i + 500])
