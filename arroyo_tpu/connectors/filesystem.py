"""Filesystem connector: file source + exactly-once committing sink.

Reference: crates/arroyo-connectors/src/filesystem (source + sink with
rolling files, partitioning, and exactly-once commits via two-phase state;
delta.rs is the table-format layer on top). Formats: json (lines), parquet,
avro (object container files).

Sink exactly-once protocol (reference sink two-phase commit,
kafka/sink/mod.rs:252-270 shape): buffered rows snapshot into state at every
checkpoint; on `commit` of an epoch the rows are written to
``part-{subtask}-{epoch}.{ext}`` via tmp-file + atomic rename, so a crash
between checkpoint and commit replays the write idempotently (same target
name) and uncommitted buffers are restored from state.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time
import uuid
from typing import Optional

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Schema
from ..config import config
from ..operators.base import Operator, SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_sink, register_source


def _list_input_files(path: str) -> list[str]:
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in sorted(files))
        return sorted(out)
    matched = sorted(_glob.glob(path))
    return matched if matched else [path]


def _read_file_rows(path: str, fmt: str) -> list[dict]:
    if fmt == "json":
        with open(path) as f:
            return [json.loads(l) for l in f if l.strip()]
    if fmt == "parquet":
        import pyarrow.parquet as pq

        table = pq.read_table(path, use_threads=False)
        return table.to_pylist()
    if fmt == "avro":
        from ..formats.avro_fmt import read_ocf

        with open(path, "rb") as f:
            _schema, rows = read_ocf(f.read())
        return rows
    raise ValueError(f"filesystem source: unknown format {fmt!r}")


class FileSystemSource(SourceOperator):
    """config: path (file, dir, or glob), format: json|parquet|avro,
    schema, event_time_field, bad_data. State: (file index, row offset) —
    subtask 0 reads (offset survives rescale, like single_file)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.path = str(cfg["path"])
        self.fmt = str(cfg.get("format", "json"))
        self.schema: Schema = cfg["schema"]
        self.event_time_field = cfg.get("event_time_field")

    def tables(self):
        return [TableSpec("f", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        from ..formats.base import rows_to_batch

        ctx = sctx.ctx
        if ctx.task_info.subtask_index != 0:
            return SourceFinishType.GRACEFUL
        tbl = ctx.table_manager.global_keyed("f")
        file_idx, row_off = tbl.get("pos", (0, 0))
        files = _list_input_files(self.path)
        batch_size = config().get("pipeline.source-batch-size")
        delay_us = config().get("testing.source-read-delay-micros", 0)
        if delay_us:
            # throttled runs need small chunks so control messages
            # (checkpoints) interleave with the data
            batch_size = min(batch_size, 8)
        while file_idx < len(files):
            rows = _read_file_rows(files[file_idx], self.fmt)
            while row_off < len(rows):
                msg = sctx.poll_control()
                if msg is not None:
                    if msg.kind == "checkpoint":
                        tbl.insert("pos", (file_idx, row_off))
                        sctx.start_checkpoint(msg.barrier)
                        if msg.barrier.then_stop:
                            return SourceFinishType.FINAL
                    elif msg.kind == "stop":
                        return SourceFinishType.IMMEDIATE
                chunk = rows[row_off : row_off + batch_size]
                row_off += len(chunk)
                collector.collect(
                    rows_to_batch(chunk, self.schema, self.event_time_field)
                )
                if delay_us:
                    import time as _time

                    _time.sleep(delay_us / 1e6 * len(chunk))
            file_idx += 1
            row_off = 0
        tbl.insert("pos", (file_idx, 0))
        return SourceFinishType.GRACEFUL


class FileSystemSink(Operator):
    """config: path (output dir), format: json|parquet|avro, schema,
    partition_fields: [col] | None, rollover_rows (default 100k).

    Buffers rows; commits them as immutable part files on the two-phase
    commit of each checkpoint epoch (see module docstring)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.dir = str(cfg["path"])
        self.fmt = str(cfg.get("format", "json"))
        self.schema: Optional[Schema] = cfg.get("schema")
        self.partition_fields: list[str] = list(cfg.get("partition_fields", ()))
        # partition value tuple -> buffered rows
        self.buf: dict[tuple, list[dict]] = {}
        self.pending_commit: dict[int, dict[tuple, list[dict]]] = {}

    def tables(self):
        return [TableSpec("b", "global_keyed")]

    def is_committing(self) -> bool:
        return True

    def on_start(self, ctx):
        tbl = ctx.table_manager.global_keyed("b")
        sub = ctx.task_info.subtask_index
        saved = tbl.get(sub)
        if saved:
            self.buf = {tuple(k): list(v) for k, v in saved.get("buf", [])}
            self.pending_commit = {
                int(e): {tuple(k): list(v) for k, v in groups}
                for e, groups in saved.get("pending", [])
            }
            # a crash after checkpoint but before commit: re-commit now
            # (idempotent: same part-file names)
            for epoch in sorted(self.pending_commit):
                self._write_epoch(ctx, epoch)

    def process_batch(self, batch, ctx, collector, input_index=0):
        rows = batch.to_pylist()
        for r in rows:
            r.pop(KEY_FIELD, None)
            key = tuple(r.get(f) for f in self.partition_fields)
            self.buf.setdefault(key, []).append(r)

    def handle_checkpoint(self, barrier, ctx, collector):
        # phase 1: move the buffer into the epoch's pending-commit set and
        # snapshot everything (reference CommittingState)
        if self.buf:
            self.pending_commit[barrier.epoch] = self.buf
            self.buf = {}
        self._snapshot(ctx)

    def handle_commit(self, epoch, ctx):
        # phase 2: durable write + forget
        self._write_epoch(ctx, epoch)

    def on_close(self, ctx, collector):
        # drain without a final checkpoint: write whatever remains,
        # including checkpointed-but-uncommitted epochs whose commit
        # message raced with task shutdown (idempotent part names)
        for epoch in sorted(self.pending_commit):
            self._write_epoch(ctx, epoch)
        if self.buf:
            epoch = 9_000_000  # "final" drain part, sorts after real epochs
            self.pending_commit[epoch] = self.buf
            self.buf = {}
            self._write_epoch(ctx, epoch)

    # ------------------------------------------------------------------

    def _snapshot(self, ctx) -> None:
        ctx.table_manager.global_keyed("b").insert(
            ctx.task_info.subtask_index,
            {
                "buf": [(list(k), list(v)) for k, v in self.buf.items()],
                "pending": [
                    (e, [(list(k), list(v)) for k, v in groups.items()])
                    for e, groups in self.pending_commit.items()
                ],
            },
        )

    def _partition_dir(self, key: tuple) -> str:
        if not self.partition_fields:
            return self.dir
        parts = [f"{f}={v}" for f, v in zip(self.partition_fields, key)]
        return os.path.join(self.dir, *parts)

    def _write_epoch(self, ctx, epoch: int) -> None:
        groups = self.pending_commit.pop(epoch, None)
        if not groups:
            return
        sub = ctx.task_info.subtask_index
        ext = {"json": "json", "parquet": "parquet", "avro": "avro"}[self.fmt]
        for key, rows in groups.items():
            d = self._partition_dir(key)
            os.makedirs(d, exist_ok=True)
            final = os.path.join(d, f"part-{sub:03d}-{epoch:07d}.{ext}")
            tmp = final + ".tmp"
            self._write_rows(tmp, rows)
            os.replace(tmp, final)

    def _write_rows(self, path: str, rows: list[dict]) -> None:
        drop = {TIMESTAMP_FIELD, KEY_FIELD}
        clean = [{k: v for k, v in r.items() if k not in drop} for r in rows]
        if self.fmt == "json":
            ts_fields = set()
            if self.schema is not None:
                ts_fields = {f.name for f in self.schema.fields if f.dtype == "timestamp"}
            from ..formats.json_fmt import format_iso_micros

            with open(path, "w") as f:
                for r in clean:
                    r = {
                        k: (format_iso_micros(v) if k in ts_fields and v is not None else v)
                        for k, v in r.items()
                    }
                    f.write(json.dumps(r, separators=(",", ":"), default=str) + "\n")
            return
        if self.fmt == "parquet":
            import pyarrow as pa
            import pyarrow.parquet as pq

            names = list(clean[0].keys()) if clean else []
            cols = {n: [r.get(n) for r in clean] for n in names}
            pq.write_table(pa.table(cols), path)
            return
        if self.fmt == "avro":
            from ..formats.avro_fmt import schema_from_table, write_ocf

            if self.schema is None:
                raise ValueError("avro filesystem sink requires a schema")
            asch = schema_from_table(self.schema.fields)
            names = [f["name"] for f in asch.fields]
            with open(path, "wb") as f:
                f.write(write_ocf(asch, [{n: r.get(n) for n in names} for r in clean]))
            return
        raise ValueError(f"filesystem sink: unknown format {self.fmt!r}")


_DELTA_TYPES = {
    "int64": "long", "int32": "integer", "uint64": "long",
    "float64": "double", "float32": "float", "bool": "boolean",
    "string": "string", "timestamp": "timestamp",
}


class DeltaSink(FileSystemSink):
    """Delta Lake table writer (reference:
    crates/arroyo-connectors/src/filesystem/delta.rs — parquet parts plus
    Delta transaction-log commits). Parts land through the same two-phase
    commit as the filesystem sink; each committed epoch then appends one
    version to ``_delta_log`` with its ``add`` actions (version 0 also
    carries ``protocol`` and ``metaData``). Versions are claimed atomically
    with O_EXCL creates, so parallel subtasks committing the same epoch
    serialize instead of clobbering; re-commits after a crash rewrite the
    same deterministic part names, and duplicate ``add`` actions for an
    identical path are a no-op to Delta readers (last action wins)."""

    def __init__(self, cfg: dict):
        cfg = dict(cfg)
        cfg["format"] = "parquet"
        super().__init__(cfg)
        if self.schema is None:
            raise ValueError("delta sink requires a schema")

    def _write_rows(self, path: str, rows: list[dict]) -> None:
        # parquet with proper logical types: Delta declares "timestamp"
        # columns in its schemaString, so the parquet column must carry a
        # timestamp logical type, not raw int64 micros
        import pyarrow as pa
        import pyarrow.parquet as pq

        # Delta protocol: partition column values live in the log's
        # partitionValues and the hive-style directory name, never in the
        # part file itself (readers materialize them; a copy in the file
        # conflicts with the inferred partition field type)
        drop = {TIMESTAMP_FIELD, KEY_FIELD, *self.partition_fields}
        clean = [{k: v for k, v in r.items() if k not in drop} for r in rows]
        ts_fields = {f.name for f in self.schema.fields if f.dtype == "timestamp"}
        names = list(clean[0].keys()) if clean else []
        arrays = []
        for n in names:
            vals = [r.get(n) for r in clean]
            if n in ts_fields:
                arrays.append(pa.array(
                    [None if v is None else int(v) for v in vals],
                    type=pa.timestamp("us"),
                ))
            else:
                arrays.append(pa.array(vals))
        pq.write_table(pa.table(arrays, names=names), path)

    def _schema_string(self) -> str:
        fields = [
            {"name": f.name, "type": _DELTA_TYPES.get(f.dtype, "string"),
             "nullable": True, "metadata": {}}
            for f in self.schema.fields
            if f.name not in (TIMESTAMP_FIELD, KEY_FIELD)
        ]
        return json.dumps({"type": "struct", "fields": fields})

    def _write_epoch(self, ctx, epoch: int) -> None:
        groups = self.pending_commit.pop(epoch, None)
        if not groups:
            return
        sub = ctx.task_info.subtask_index
        adds = []
        now_ms = int(time.time() * 1000)
        for key, rows in groups.items():
            d = self._partition_dir(key)
            os.makedirs(d, exist_ok=True)
            final = os.path.join(d, f"part-{sub:03d}-{epoch:07d}.parquet")
            tmp = final + ".tmp"
            self._write_rows(tmp, rows)
            os.replace(tmp, final)
            rel = os.path.relpath(final, self.dir)
            adds.append({"add": {
                "path": rel.replace(os.sep, "/"),
                "partitionValues": {
                    f: str(v) for f, v in zip(self.partition_fields, key)
                },
                "size": os.path.getsize(final),
                "modificationTime": now_ms,
                "dataChange": True,
            }})
        self._commit_log(adds, now_ms)

    def _commit_log(self, actions: list[dict], now_ms: int) -> None:
        log_dir = os.path.join(self.dir, "_delta_log")
        os.makedirs(log_dir, exist_ok=True)
        while True:
            versions = [
                int(fn.split(".")[0]) for fn in os.listdir(log_dir)
                if fn.endswith(".json") and fn.split(".")[0].isdigit()
            ]
            v = (max(versions) + 1) if versions else 0
            entry = list(actions)
            if v == 0:
                entry = [
                    {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
                    {"metaData": {
                        "id": uuid.uuid4().hex,
                        "format": {"provider": "parquet", "options": {}},
                        "schemaString": self._schema_string(),
                        "partitionColumns": list(self.partition_fields),
                        "configuration": {},
                        "createdTime": now_ms,
                    }},
                ] + entry
            path = os.path.join(log_dir, f"{v:020d}.json")
            # atomic publish: fully write a tmp file, then claim the version
            # with a hard link (fails if another subtask won) — a crash can
            # never leave a truncated version in the log
            tmp = os.path.join(log_dir, f".{uuid.uuid4().hex}.tmp")
            with open(tmp, "w") as f:
                for a in entry:
                    f.write(json.dumps(a, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, path)
            except FileExistsError:
                os.unlink(tmp)
                continue  # another subtask claimed this version; retry
            os.unlink(tmp)
            return


register_source("filesystem")(FileSystemSource)
register_sink("filesystem")(FileSystemSink)
register_sink("delta")(DeltaSink)
