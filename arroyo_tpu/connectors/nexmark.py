"""Nexmark benchmark source.

Deterministic, splittable generator for the NEXMark auction benchmark
(reference: crates/arroyo-connectors/src/nexmark/operator.rs — event kinds
:68-160, GeneratorConfig :431, deterministic event-number scheme :514-530,
split() across subtasks :493). Re-designed vectorized: a whole micro-batch of
events is derived from its event numbers with numpy uint64 lanes (splitmix64
counter RNG), so generation keeps up with a TPU consumer; subtask i of p owns
event numbers n with n % p == i.

Event mix per 50 events (standard NEXMark proportions): 1 person, 3 auctions,
46 bids. The three entity types are flattened into presence-flagged column
groups ("person.*", "auction.*", "bid.*" with boolean "person"/"auction"/
"bid" presence columns) instead of Arrow struct columns; SQL predicates like
``bid IS NOT NULL`` resolve against the presence columns.
"""

from __future__ import annotations

import time

import numpy as np

from ..batch import TIMESTAMP_FIELD, Batch, Field, Schema
from ..config import config
from ..hashing import splitmix64
from ..operators.base import SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_source

FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10
PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
TOTAL_PROPORTION = PERSON_PROPORTION + AUCTION_PROPORTION + BID_PROPORTION  # 50
HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100

NEXMARK_SCHEMA = Schema.of(
    [
        Field("event_type", "int32"),  # 0=person 1=auction 2=bid
        Field("person", "bool"),
        Field("person.id", "int64"),
        Field("person.name", "string"),
        Field("person.email_address", "string"),
        Field("person.city", "string"),
        Field("person.state", "string"),
        Field("auction", "bool"),
        Field("auction.id", "int64"),
        Field("auction.item_name", "string"),
        Field("auction.initial_bid", "int64"),
        Field("auction.reserve", "int64"),
        Field("auction.expires", "int64"),
        Field("auction.seller", "int64"),
        Field("auction.category", "int64"),
        Field("bid", "bool"),
        Field("bid.auction", "int64"),
        Field("bid.bidder", "int64"),
        Field("bid.price", "int64"),
        Field("bid.channel", "string"),
        Field("bid.datetime", "int64"),
        Field(TIMESTAMP_FIELD, "int64"),
    ]
)

_US_STATES = np.array(["AZ", "CA", "ID", "OR", "WA", "WY"], dtype=object)
_CITIES = np.array(
    ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland", "Bend",
     "Redmond", "Seattle", "Kent", "Cheyenne"],
    dtype=object,
)
_CHANNELS = np.array(["Google", "Facebook", "Baidu", "Apple"], dtype=object)


def _rng(n: np.ndarray, salt: int) -> np.ndarray:
    return splitmix64(n ^ np.uint64((salt * 0x9E3779B97F4A7C15 | 1) & ((1 << 64) - 1)))


class NexmarkSource(SourceOperator):
    """config: event_rate (events/s across all subtasks, 0 = unthrottled),
    event_count (total; None = unbounded), first_event_micros,
    inter_event_micros (event-time step; default from event_rate or 1000us),
    bids_only (skip person/auction columns for pure-bid benches: False)."""

    def __init__(self, cfg: dict):
        self.event_rate = cfg.get("event_rate", 0)
        self.event_count = cfg.get("event_count")
        self.first_event_micros = cfg.get("first_event_micros", 1_600_000_000_000_000)
        if cfg.get("inter_event_micros") is not None:
            self.inter_event_micros = cfg["inter_event_micros"]
        elif self.event_rate:
            self.inter_event_micros = max(int(1e6 / self.event_rate), 1)
        else:
            self.inter_event_micros = 1000
        self.include_strings = cfg.get("include_strings", True)
        # projection pushdown: planner-provided set of columns the query
        # reads (presence flags + timestamp always generated); None = all
        self.columns = set(cfg["columns"]) if cfg.get("columns") else None

    def tables(self):
        return [TableSpec("s", "global_keyed")]

    def _generate(self, numbers: np.ndarray) -> Batch:
        """Vectorized event synthesis for the given absolute event numbers.

        ``self.columns`` (planner projection pushdown, like DataFusion's
        projection pushdown into table scans) restricts synthesis to the
        columns a query actually reads; presence flags and the timestamp are
        always produced."""
        n = numbers.astype(np.uint64)
        count = len(n)
        need = self.columns  # None = all
        def want(c):
            return need is None or c in need
        epoch = (n // np.uint64(TOTAL_PROPORTION)).astype(np.int64)
        offset = (n % np.uint64(TOTAL_PROPORTION)).astype(np.int64)
        is_person = offset < PERSON_PROPORTION
        is_auction = (~is_person) & (offset < PERSON_PROPORTION + AUCTION_PROPORTION)
        is_bid = ~(is_person | is_auction)
        ts = self.first_event_micros + n.astype(np.int64) * self.inter_event_micros

        # ids so far (exclusive of current epoch, conservative "active" sets)
        max_person = FIRST_PERSON_ID + epoch * PERSON_PROPORTION
        max_auction = FIRST_AUCTION_ID + epoch * AUCTION_PROPORTION

        r0 = _rng(n, 1)
        r1 = _rng(n, 2)

        auction_id = None
        if want("auction.id") or want("auction.item_name"):
            auction_id = np.where(
                is_auction, FIRST_AUCTION_ID + epoch * AUCTION_PROPORTION + (offset - PERSON_PROPORTION), 0
            ).astype(np.int64)

        cols: dict[str, np.ndarray] = {
            "person": is_person,
            "auction": is_auction,
            "bid": is_bid,
            TIMESTAMP_FIELD: ts,
        }
        if want("event_type"):
            cols["event_type"] = np.where(is_person, 0, np.where(is_auction, 1, 2)).astype(np.int32)
        if want("person.id"):
            cols["person.id"] = np.where(is_person, FIRST_PERSON_ID + epoch, 0).astype(np.int64)
        if auction_id is not None:
            cols["auction.id"] = auction_id
        if want("bid.auction"):
            # bids: hot auctions with ratio 1/HOT of uniform traffic
            recent_window = np.maximum(max_auction - FIRST_AUCTION_ID, 1)
            hot_auction = np.maximum(
                max_auction - 1 - (r0 % np.uint64(HOT_AUCTION_RATIO)).astype(np.int64), FIRST_AUCTION_ID)
            cold_auction = FIRST_AUCTION_ID + (r0.astype(np.int64) % recent_window)
            cols["bid.auction"] = np.where(
                is_bid,
                np.where((r1 % np.uint64(100)).astype(np.int64) < 90, hot_auction, cold_auction),
                0,
            )
        if want("bid.bidder"):
            r2 = _rng(n, 3)
            r3 = _rng(n, 4)
            recent_people = np.maximum(max_person - FIRST_PERSON_ID, 1)
            hot_bidder = np.maximum(
                max_person - 1 - (r2 % np.uint64(HOT_BIDDER_RATIO)).astype(np.int64), FIRST_PERSON_ID)
            cold_bidder = FIRST_PERSON_ID + (r2.astype(np.int64) % recent_people)
            cols["bid.bidder"] = np.where(
                is_bid,
                np.where((r3 % np.uint64(100)).astype(np.int64) < 90, hot_bidder, cold_bidder),
                0,
            )
        if want("bid.price"):
            cols["bid.price"] = np.where(is_bid, (100 + (r1 % np.uint64(9_999_900))).astype(np.int64), 0)
        if want("auction.initial_bid"):
            cols["auction.initial_bid"] = np.where(is_auction, 100 + (r1 % np.uint64(1000)).astype(np.int64), 0)
        if want("auction.reserve"):
            cols["auction.reserve"] = np.where(is_auction, 500 + (_rng(n, 3) % np.uint64(2000)).astype(np.int64), 0)
        if want("auction.expires"):
            cols["auction.expires"] = np.where(
                is_auction, ts + (1 + (_rng(n, 4) % np.uint64(60))).astype(np.int64) * 1_000_000, 0)
        if want("auction.seller"):
            cols["auction.seller"] = np.where(
                is_auction, FIRST_PERSON_ID + (r0.astype(np.int64) % np.maximum(max_person - FIRST_PERSON_ID, 1)), 0
            )
        if want("auction.category"):
            cols["auction.category"] = np.where(is_auction, FIRST_CATEGORY_ID + (r0.astype(np.int64) % 5), 0)
        if want("bid.datetime"):
            cols["bid.datetime"] = np.where(is_bid, ts // 1000, 0)
        if self.include_strings:
            r2s = _rng(n, 3)
            if want("person.name"):
                cols["person.name"] = np.where(
                    is_person, np.char.add("person-", epoch.astype(str)).astype(object), None
                )
            if want("person.email_address"):
                cols["person.email_address"] = np.where(
                    is_person, np.char.add(np.char.add("p", epoch.astype(str)), "@example.com").astype(object), None
                )
            if want("person.city"):
                cols["person.city"] = np.where(is_person, _CITIES[(r1 % np.uint64(len(_CITIES))).astype(np.int64)], None)
            if want("person.state"):
                cols["person.state"] = np.where(is_person, _US_STATES[(r2s % np.uint64(len(_US_STATES))).astype(np.int64)], None)
            if want("auction.item_name"):
                cols["auction.item_name"] = np.where(
                    is_auction, np.char.add("item-", auction_id.astype(str)).astype(object), None
                )
            if want("bid.channel"):
                cols["bid.channel"] = np.where(is_bid, _CHANNELS[(r2s % np.uint64(len(_CHANNELS))).astype(np.int64)], None)
        return Batch(cols)

    def run(self, sctx, collector) -> SourceFinishType:
        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        p = ctx.task_info.parallelism
        tbl = ctx.table_manager.global_keyed("s")
        i = tbl.get(sub, 0)  # index within this subtask's event-number stream
        batch_size = config().get("pipeline.source-batch-size")
        per_task_count = None
        if self.event_count is not None:
            per_task_count = (self.event_count - sub + p - 1) // p
        rate_per_task = self.event_rate / p if self.event_rate else 0
        started = time.monotonic()

        def control():
            msg = sctx.poll_control()
            if msg is None:
                return None
            if msg.kind == "checkpoint":
                tbl.insert(sub, i)
                sctx.start_checkpoint(msg.barrier)
                if msg.barrier.then_stop:
                    return SourceFinishType.FINAL
            elif msg.kind == "stop":
                return SourceFinishType.IMMEDIATE
            return None

        while per_task_count is None or i < per_task_count:
            r = control()
            if r is not None:
                return r
            b = batch_size
            if per_task_count is not None:
                b = min(b, per_task_count - i)
            local = np.arange(i, i + b, dtype=np.uint64)
            numbers = local * np.uint64(p) + np.uint64(sub)
            collector.collect(self._generate(numbers))
            i += b
            if rate_per_task:
                target = started + i / rate_per_task
                while True:
                    delay = target - time.monotonic()
                    if delay <= 0:
                        break
                    r = control()
                    if r is not None:
                        return r
                    time.sleep(min(delay, 0.05))
        # keep the offset table current for the run loop's final snapshot
        tbl.insert(sub, i)
        return SourceFinishType.GRACEFUL


register_source("nexmark")(NexmarkSource)
