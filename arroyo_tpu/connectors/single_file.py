"""single_file test connector: deterministic line-delimited JSON file
source/sink (reference crates/arroyo-connectors/src/single_file — the fixture
the SQL smoke-test harness is built on, SURVEY §4).

The source checkpoints its line offset; the sink buffers rows in state and
writes the file contents on checkpoint/close so restores don't duplicate
output (matching the reference's committing file sink behavior).
"""

from __future__ import annotations

import os

from ..batch import Batch, Schema
from ..config import config
from ..formats.json_fmt import JsonDeserializer, serialize_json_lines
from ..operators.base import Operator, SourceOperator, TableSpec
from ..types import SourceFinishType
from . import register_sink, register_source


class SingleFileSource(SourceOperator):
    """config: path, schema: Schema, event_time_field: str|None,
    bad_data: "fail"|"drop"."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.path = cfg["path"]
        self.schema: Schema = cfg["schema"]
        self.event_time_field = cfg.get("event_time_field")
        self.bad_data = cfg.get("bad_data", "fail")

    def tables(self):
        return [TableSpec("s", "global_keyed")]

    def run(self, sctx, collector) -> SourceFinishType:
        ctx = sctx.ctx
        sub = ctx.task_info.subtask_index
        if sub != 0:
            # only subtask 0 reads the file (reference single_file/source.rs:96)
            # so the line offset survives restores at any parallelism.
            # Restore CLONES subtask 0's offset into this subtask's table
            # (global tables merge across shards on load); drop the clone
            # before draining, or our "final" snapshot would persist a stale
            # copy of the reader's offset that a later restore could merge
            # OVER the live one — replaying the file from the stale point
            # while the sink keeps its lines (duplicated output).
            ctx.table_manager.global_keyed("s").data.clear()
            return SourceFinishType.GRACEFUL
        tbl = ctx.table_manager.global_keyed("s")
        offset = tbl.get(sub, 0)
        from ..formats.registry import make_deserializer

        de = make_deserializer(self.cfg, self.schema, task_info=ctx.task_info)
        with open(self.path) as f:
            lines = f.read().splitlines()
        # deterministic split across subtasks: round-robin by line number
        i = offset
        my_lines = lines
        # test-only throttle so mid-stream checkpoints are meaningful
        # (reference smoke tests get this from their rate-limited sources)
        delay_us = config().get("testing.source-read-delay-micros", 0)
        # deterministic mid-stream gate (reference smoke_tests.rs:300-356
        # drives the source by hand instead): after reading half the input,
        # hold — still answering control/checkpoints — until ``gate_epochs``
        # barriers have been processed. Guarantees checkpoints land
        # mid-stream regardless of scheduling, so the restore leg of the
        # smoke harness can never be silently skipped.
        gate_epochs = config().get("testing.source-gate-epochs", 0)
        gate_line = len(my_lines) // 2
        seen_epochs = 0
        while i < len(my_lines):
            if delay_us:
                import time as _time

                _time.sleep(delay_us / 1e6)
            holding = gate_epochs and seen_epochs < gate_epochs and i >= gate_line
            msg = sctx.poll_control()
            if msg is None and holding:
                import time as _time

                _time.sleep(0.001)
                continue
            if msg is not None:
                if msg.kind == "checkpoint":
                    b = de.flush()
                    if b is not None:
                        collector.collect(b)
                    tbl.insert(sub, i)
                    sctx.start_checkpoint(msg.barrier)
                    seen_epochs += 1
                    if msg.barrier.then_stop:
                        return SourceFinishType.FINAL
                elif msg.kind == "stop":
                    return SourceFinishType.IMMEDIATE
                if holding:
                    continue
            line = my_lines[i]
            i += 1
            if line.strip():
                de.deserialize(line)
            if de.should_flush():
                b = de.flush()
                if b is not None:
                    collector.collect(b)
        b = de.flush()
        if b is not None:
            collector.collect(b)
        # keep the offset table current: the run loop snapshots it into the
        # "final" checkpoint after a graceful drain
        tbl.insert(sub, i)
        return SourceFinishType.GRACEFUL


class SingleFileSink(Operator):
    """config: path. Buffers emitted lines in a global-keyed state table and
    rewrites the output file at each checkpoint/close (exactly-once)."""

    def __init__(self, cfg: dict):
        self.path = cfg["path"]
        self.schema = cfg.get("schema")
        self.lines: list[str] = []

    def tables(self):
        return [TableSpec("out", "global_keyed")]

    def on_start(self, ctx):
        tbl = ctx.table_manager.global_keyed("out")
        self.lines = list(tbl.get(ctx.task_info.subtask_index, []))

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.lines.extend(serialize_json_lines(batch, self.schema))

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.global_keyed("out").insert(
            ctx.task_info.subtask_index, list(self.lines)
        )
        self._write(ctx)

    def on_close(self, ctx, collector):
        self._write(ctx)

    def _write(self, ctx):
        # each subtask appends to its own shard file; parallelism 1 in tests
        path = self.path
        if ctx.task_info.parallelism > 1:
            path = f"{self.path}.{ctx.task_info.subtask_index}"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            for line in self.lines:
                f.write(line + "\n")


register_source("single_file")(SingleFileSource)
register_sink("single_file")(SingleFileSink)
