// Tiny SVG chart helpers for the per-operator metric panels (reference
// webui uses chart components over /metrics; same data, hand-rolled SVG).

// ring-buffered time series per key, fed by successive metric polls
export class SeriesStore {
  constructor(cap = 60) { this.cap = cap; this.series = new Map(); }
  push(key, value) {
    if (!this.series.has(key)) this.series.set(key, []);
    const s = this.series.get(key);
    s.push(Number(value) || 0);
    if (s.length > this.cap) s.shift();
  }
  get(key) { return this.series.get(key) || []; }
}

export function sparkline(points, w = 120, h = 26) {
  if (!points.length) return `<svg width="${w}" height="${h}"></svg>`;
  const max = Math.max(...points, 1e-9);
  const step = points.length > 1 ? w / (points.length - 1) : w;
  const xy = points.map((v, i) =>
    `${(i * step).toFixed(1)},${(h - 2 - (v / max) * (h - 6)).toFixed(1)}`);
  const line = `M${xy.join(" L")}`;
  const fill = `${line} L${w},${h} L0,${h} Z`;
  return `<svg width="${w}" height="${h}">
    <path class="sparkfill" d="${fill}"/>
    <path class="spark" d="${line}"/></svg>`;
}

export function backpressureBar(frac) {
  const pct = Math.round(Math.min(Math.max(frac ?? 0, 0), 1) * 100);
  return `<div class="bp-bar ${pct > 70 ? "hot" : ""}" title="${pct}%">
    <i style="width:${pct}%"></i></div>`;
}
