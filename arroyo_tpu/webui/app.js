// SPA shell: hash router + shared API helper (reference webui App.tsx /
// react-router; same surface, no build step).
import { jobsView } from "/webui/jobs.js";
import { pipelinesView } from "/webui/pipelines.js";
import { connectionsView } from "/webui/connections.js";
import { udfsView } from "/webui/udfs.js";

export async function api(method, path, body) {
  const r = await fetch(path, {
    method,
    headers: { "Content-Type": "application/json" },
    body: body ? JSON.stringify(body) : undefined,
  });
  const j = await r.json();
  if (!r.ok) throw new Error(j.error || r.statusText);
  return j;
}

export const el = (html) => {
  const t = document.createElement("template");
  t.innerHTML = html.trim();
  return t.content.firstChild;
};

export const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
  (c) => ({ "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;" }[c]));

const VIEWS = {
  jobs: jobsView,
  pipelines: pipelinesView,
  connections: connectionsView,
  udfs: udfsView,
};

let teardown = null;
let routeSeq = 0;

async function route() {
  const hash = location.hash || "#/jobs";
  const [, view, arg] = hash.split("/");
  const fn = VIEWS[view] || jobsView;
  document.querySelectorAll("#nav a").forEach((a) =>
    a.classList.toggle("active", a.dataset.view === (VIEWS[view] ? view : "jobs")));
  if (teardown) { teardown(); teardown = null; }
  const mount = document.getElementById("view");
  mount.innerHTML = "";
  const seq = ++routeSeq;
  const t = await fn(mount, arg);
  if (seq === routeSeq) {
    teardown = t;       // still the active view
  } else if (t) {
    t();                // superseded while mounting: tear down immediately
  }
}

window.addEventListener("hashchange", route);
route();
