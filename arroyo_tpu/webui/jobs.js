// Jobs view: live list, and per-job detail with the planned dataflow
// graph, checkpoint history, per-operator rate/backpressure charts, and
// output preview (reference PipelineDetails.tsx + PipelineGraph.tsx +
// Checkpoints.tsx + OperatorDetail.tsx over the same endpoints).
import { api, el, esc } from "/webui/app.js";
import { renderGraph } from "/webui/graph.js";
import { SeriesStore, sparkline, backpressureBar } from "/webui/charts.js";

export async function jobsView(mount) {
  mount.appendChild(el(`<div>
    <div class="panel">
      <h2>Jobs</h2>
      <table id="jobs"><thead><tr>
        <th>job</th><th>pipeline</th><th>state</th><th>epoch</th>
        <th>restarts</th><th>parallelism</th><th></th>
      </tr></thead><tbody></tbody></table>
    </div>
    <div id="detail" style="display:none">
      <div class="panel">
        <h2 id="dtitle">Job</h2>
        <div id="dgraph" class="sub">select a job to see its dataflow</div>
      </div>
      <div class="cols">
        <div>
          <div class="panel">
            <h2>Checkpoints</h2>
            <table id="ckpts"><thead><tr>
              <th>epoch</th><th>state</th><th>at</th>
            </tr></thead><tbody></tbody></table>
          </div>
          <div class="panel">
            <h2>Control</h2>
            <div class="row">
              <button class="ghost" id="stopck">stop w/ checkpoint</button>
              <button class="danger" id="stopnow">stop now</button>
            </div>
            <div class="row">
              <input id="rescale-n" type="number" min="1" value="2"
                     style="width:70px">
              <button class="ghost" id="rescale">rescale</button>
              <span id="cmsg" class="sub"></span>
            </div>
          </div>
        </div>
        <div>
          <div class="panel">
            <h2>Operators</h2>
            <table id="opstats"><thead><tr>
              <th>operator</th><th>msg/s</th><th>rate</th><th>sent</th>
              <th>backpressure</th>
            </tr></thead><tbody></tbody></table>
          </div>
          <div class="panel">
            <h2>Output preview</h2>
            <pre id="doutput">(no preview rows)</pre>
          </div>
        </div>
      </div>
    </div>
  </div>`));

  let selected = null;
  let selectedPipeline = null;
  let graphData = null;
  const series = new SeriesStore();
  const $ = (s) => mount.querySelector(s);

  async function showDetail(jobId, pipelineId) {
    selected = jobId;
    selectedPipeline = pipelineId;
    graphData = null;
    $("#detail").style.display = "block";
    $("#dtitle").textContent = `Job ${jobId}`;
    try {
      graphData = await api("GET", `/api/v1/pipelines/${pipelineId}/graph`);
    } catch (e) {
      $("#dgraph").innerHTML = `<span class="err">${esc(e.message)}</span>`;
    }
    await refreshDetail();
  }

  async function refreshDetail() {
    if (!selected) return;
    try {
      const m = await api("GET", `/api/v1/jobs/${selected}/metrics`);
      const ops = m.data || {};
      for (const [op, v] of Object.entries(ops))
        series.push(`${selected}:${op}`, v.messages_per_sec ?? 0);
      if (graphData)
        $("#dgraph").innerHTML = renderGraph(graphData, ops);
      const tb = $("#opstats tbody");
      tb.innerHTML = "";
      for (const [op, v] of Object.entries(ops)) {
        const tr = document.createElement("tr");
        tr.innerHTML = `<td>${esc(op)}</td>
          <td>${v.messages_per_sec ?? ""}</td>
          <td>${sparkline(series.get(`${selected}:${op}`))}</td>
          <td>${v.arroyo_worker_messages_sent ?? 0}</td>
          <td>${backpressureBar(v.backpressure)}</td>`;
        tb.appendChild(tr);
      }
      const ck = await api("GET", `/api/v1/jobs/${selected}/checkpoints`);
      const ctb = $("#ckpts tbody");
      ctb.innerHTML = "";
      for (const c of (ck.data || []).slice(-12).reverse()) {
        const tr = document.createElement("tr");
        tr.innerHTML = `<td>${c.epoch}</td>
          <td><span class="state ${c.state === "complete" ? "Running" : "Created"}">${esc(c.state)}</span></td>
          <td class="sub">${new Date(c.time * 1000).toLocaleTimeString()}</td>`;
        ctb.appendChild(tr);
      }
      const out = await api("GET", `/api/v1/jobs/${selected}/output`);
      const lines = (out.data || []).map((r) => r.line);
      $("#doutput").textContent =
        lines.slice(-40).join("\n") || "(no preview rows)";
    } catch (e) { /* job may have been deleted mid-poll */ }
  }

  $("#stopck").onclick = () =>
    api("PATCH", `/api/v1/jobs/${selected}`, { stop: "checkpoint" })
      .then(refresh).catch((e) => { $("#cmsg").textContent = e.message; });
  $("#stopnow").onclick = () =>
    api("PATCH", `/api/v1/jobs/${selected}`, { stop: "immediate" })
      .then(refresh).catch((e) => { $("#cmsg").textContent = e.message; });
  $("#rescale").onclick = () =>
    api("PATCH", `/api/v1/jobs/${selected}`,
        { parallelism: Number($("#rescale-n").value) })
      .then((r) => { $("#cmsg").textContent =
        `rescaling to ${r.desired_parallelism}`; refresh(); })
      .catch((e) => { $("#cmsg").textContent = e.message; });

  async function refresh() {
    try {
      const pls = await api("GET", "/api/v1/pipelines");
      const pipelines = Object.fromEntries(pls.data.map((p) => [p.id, p]));
      const jobs = await api("GET", "/api/v1/jobs");
      const tb = $("#jobs tbody");
      tb.innerHTML = "";
      for (const j of jobs.data) {
        const pl = pipelines[j.pipeline_id];
        const tr = document.createElement("tr");
        tr.innerHTML = `<td><a data-job="${esc(j.id)}"
            data-pl="${esc(j.pipeline_id)}">${esc(j.id)}</a></td>
          <td>${esc(pl ? pl.name : j.pipeline_id)}</td>
          <td><span class="state ${esc(j.state)}">${esc(j.state)}</span></td>
          <td>${j.checkpoint_epoch}</td><td>${j.restarts}</td>
          <td>${pl ? pl.parallelism : ""}${j.desired_parallelism
            ? " → " + j.desired_parallelism : ""}</td>
          <td></td>`;
        tr.querySelector("a").onclick = () => showDetail(j.id, j.pipeline_id);
        tb.appendChild(tr);
      }
    } catch (e) { /* api restarting */ }
    refreshDetail();
  }

  refresh();
  const timer = setInterval(refresh, 2000);
  return () => clearInterval(timer);
}
