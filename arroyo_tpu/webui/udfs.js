// UDFs view (reference UdfsIndex / UdfEditTab): register python/c++ UDFs
// against /api/v1/udfs, list and drop them.
import { api, el, esc } from "/webui/app.js";

export async function udfsView(mount) {
  mount.appendChild(el(`<div class="cols">
    <div class="panel">
      <h2>New UDF</h2>
      <div class="row">
        <input id="u-name" placeholder="name" style="flex:1">
        <select id="u-lang"><option>python</option><option>cpp</option></select>
        <input id="u-ret" placeholder="return dtype" value="int64"
               style="width:110px">
      </div>
      <div class="row"><textarea id="u-src" spellcheck="false"
        placeholder="def my_udf(x):&#10;    return x * 2"></textarea></div>
      <div class="row">
        <button id="u-create">Register</button>
        <span id="u-msg" class="sub"></span>
      </div>
    </div>
    <div class="panel">
      <h2>Registered UDFs</h2>
      <table id="udfs"><thead><tr>
        <th>name</th><th>language</th><th>returns</th><th></th>
      </tr></thead><tbody></tbody></table>
    </div>
  </div>`));
  const $ = (s) => mount.querySelector(s);

  $("#u-create").onclick = async () => {
    try {
      await api("POST", "/api/v1/udfs", {
        name: $("#u-name").value, language: $("#u-lang").value,
        source: $("#u-src").value, return_dtype: $("#u-ret").value });
      $("#u-msg").innerHTML = '<span class="ok">registered</span>';
      refresh();
    } catch (e) { $("#u-msg").innerHTML = `<span class="err">${esc(e.message)}</span>`; }
  };

  async function refresh() {
    try {
      const r = await api("GET", "/api/v1/udfs");
      const tb = $("#udfs tbody");
      tb.innerHTML = "";
      for (const u of r.udfs || []) {
        const tr = document.createElement("tr");
        tr.innerHTML = `<td>${esc(u.name)}</td><td>${esc(u.language)}</td>
          <td>${esc(u.return_dtype)}</td><td></td>`;
        const del = el(`<a>delete</a>`);
        del.onclick = () =>
          api("DELETE", `/api/v1/udfs/${encodeURIComponent(u.name)}`)
            .then(refresh).catch((e) => alert(e.message));
        tr.lastElementChild.appendChild(del);
        tb.appendChild(tr);
      }
    } catch (e) { /* transient */ }
  }

  refresh();
  const timer = setInterval(refresh, 4000);
  return () => clearInterval(timer);
}
