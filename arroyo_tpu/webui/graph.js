// Dataflow DAG renderer (reference PipelineGraph.tsx, which uses reactflow;
// here a layered longest-path layout drawn as plain SVG).
import { esc } from "/webui/app.js";

export function renderGraph(g, metricsByOp = {}) {
  // longest-path layering: column = max(parent column) + 1
  const depth = {};
  for (const n of g.nodes) depth[n.id] = 0;
  let changed = true;
  let guard = 0;
  while (changed && guard++ < 100) {
    changed = false;
    for (const e of g.edges) {
      if (depth[e.dst] < depth[e.src] + 1) {
        depth[e.dst] = depth[e.src] + 1;
        changed = true;
      }
    }
  }
  const cols = {};
  for (const n of g.nodes) (cols[depth[n.id]] = cols[depth[n.id]] || []).push(n);
  const W = 168, H = 46, GX = 60, GY = 18;
  const ncols = Object.keys(cols).length;
  const maxRows = Math.max(...Object.values(cols).map((c) => c.length));
  const width = ncols * (W + GX) + GX / 2;
  const height = Math.max(maxRows * (H + GY) + GY, 120);
  const pos = {};
  for (const [c, nodes] of Object.entries(cols)) {
    const x = Number(c) * (W + GX) + GX / 2;
    const total = nodes.length * (H + GY) - GY;
    nodes.forEach((n, i) => {
      pos[n.id] = { x, y: (height - total) / 2 + i * (H + GY) };
    });
  }
  const parts = [];
  for (const e of g.edges) {
    const a = pos[e.src], b = pos[e.dst];
    if (!a || !b) continue;
    const x1 = a.x + W, y1 = a.y + H / 2, x2 = b.x, y2 = b.y + H / 2;
    const mx = (x1 + x2) / 2;
    parts.push(`<path class="gedge ${e.type === "shuffle" ? "shuffle" : ""}"
      d="M${x1},${y1} C${mx},${y1} ${mx},${y2} ${x2},${y2}"/>`);
  }
  for (const n of g.nodes) {
    const p = pos[n.id];
    const kind = n.op === "source" ? "source" : n.op === "sink" ? "sink" : "";
    const m = metricsByOp[n.id];
    const sub = m && m.messages_per_sec != null
      ? `${m.messages_per_sec}/s` : `p=${n.parallelism}`;
    const label = esc((n.description || n.op).slice(0, 24));
    parts.push(`<g class="gnode ${kind}" transform="translate(${p.x},${p.y})">
      <rect width="${W}" height="${H}" rx="6"/>
      <text x="9" y="19">${esc(n.op)}</text>
      <text x="9" y="35" class="gsub">${label} · ${esc(sub)}</text>
    </g>`);
  }
  return `<svg class="graph" viewBox="0 0 ${width} ${height}"
    style="max-height:${Math.min(height + 20, 420)}px">
    <defs><marker id="arrow" viewBox="0 0 8 8" refX="7" refY="4"
      markerWidth="7" markerHeight="7" orient="auto">
      <path d="M0,0 L8,4 L0,8 z" fill="#8b96a5"/></marker></defs>
    ${parts.join("\n")}</svg>`;
}
