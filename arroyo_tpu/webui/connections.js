// Connections view (reference Connections.tsx / CreateConnection.tsx /
// ChooseConnector.tsx / DefineSchema.tsx): connector catalog, connection
// profiles and connection tables CRUD with spec testing.
import { api, el, esc } from "/webui/app.js";

export async function connectionsView(mount) {
  mount.appendChild(el(`<div class="cols">
    <div>
      <div class="panel">
        <h2>New connection table</h2>
        <div class="row">
          <input id="ct-name" placeholder="name" style="flex:1">
          <select id="ct-kind"><option>source</option><option>sink</option></select>
        </div>
        <div class="row">
          <select id="ct-connector" style="flex:1"></select>
          <select id="ct-profile" style="flex:1"><option value="">no profile</option></select>
        </div>
        <div class="row"><textarea id="ct-config" style="height:72px"
          placeholder='{"path": "/data/in.json", "format": "json"}'></textarea></div>
        <div class="row"><textarea id="ct-schema" style="height:72px"
          placeholder='[{"name": "x", "type": "BIGINT"}]'></textarea></div>
        <div class="row">
          <button class="ghost" id="ct-test">Test</button>
          <button id="ct-create">Create</button>
          <span id="ct-msg" class="sub"></span>
        </div>
      </div>
      <div class="panel">
        <h2>New profile</h2>
        <div class="row">
          <input id="cp-name" placeholder="name" style="flex:1">
          <select id="cp-connector" style="flex:1"></select>
        </div>
        <div class="row"><textarea id="cp-config" style="height:56px"
          placeholder='{"bootstrap_servers": "broker:9092"}'></textarea></div>
        <div class="row">
          <button id="cp-create">Create profile</button>
          <span id="cp-msg" class="sub"></span>
        </div>
      </div>
    </div>
    <div>
      <div class="panel">
        <h2>Connection tables</h2>
        <table id="cts"><thead><tr>
          <th>name</th><th>connector</th><th>type</th><th>fields</th><th></th>
        </tr></thead><tbody></tbody></table>
      </div>
      <div class="panel">
        <h2>Profiles</h2>
        <table id="cps"><thead><tr>
          <th>name</th><th>connector</th><th></th>
        </tr></thead><tbody></tbody></table>
      </div>
      <div class="panel">
        <h2>Connector catalog</h2>
        <div id="catalog" class="sub"></div>
      </div>
    </div>
  </div>`));
  const $ = (s) => mount.querySelector(s);

  const spec = () => ({
    name: $("#ct-name").value,
    connector: $("#ct-connector").value,
    table_type: $("#ct-kind").value,
    config: JSON.parse($("#ct-config").value || "{}"),
    schema_fields: JSON.parse($("#ct-schema").value || "[]"),
    ...($("#ct-profile").value ? { profile_id: $("#ct-profile").value } : {}),
  });

  $("#ct-test").onclick = async () => {
    try {
      const r = await api("POST", "/api/v1/connection_tables/test", spec());
      $("#ct-msg").innerHTML = r.ok ? '<span class="ok">ok</span>'
        : `<span class="err">${esc(r.error)}</span>`;
    } catch (e) { $("#ct-msg").innerHTML = `<span class="err">${esc(e.message)}</span>`; }
  };
  $("#ct-create").onclick = async () => {
    try {
      await api("POST", "/api/v1/connection_tables", spec());
      $("#ct-msg").innerHTML = '<span class="ok">created</span>';
      refresh();
    } catch (e) { $("#ct-msg").innerHTML = `<span class="err">${esc(e.message)}</span>`; }
  };
  $("#cp-create").onclick = async () => {
    try {
      await api("POST", "/api/v1/connection_profiles", {
        name: $("#cp-name").value, connector: $("#cp-connector").value,
        config: JSON.parse($("#cp-config").value || "{}") });
      $("#cp-msg").innerHTML = '<span class="ok">created</span>';
      refresh();
    } catch (e) { $("#cp-msg").innerHTML = `<span class="err">${esc(e.message)}</span>`; }
  };

  async function refresh() {
    try {
      const cat = await api("GET", "/api/v1/connectors");
      const sources = cat.sources || [];
      const sinks = cat.sinks || [];
      $("#catalog").innerHTML =
        `<b>sources</b>: ${sources.map(esc).join(", ")}<br>` +
        `<b>sinks</b>: ${sinks.map(esc).join(", ")}`;
      const all = [...new Set([...sources, ...sinks])].sort();
      for (const sel of ["#ct-connector", "#cp-connector"]) {
        const cur = $(sel).value;
        $(sel).innerHTML = all.map((c) =>
          `<option${c === cur ? " selected" : ""}>${esc(c)}</option>`).join("");
      }
      const cts = await api("GET", "/api/v1/connection_tables");
      const tb = $("#cts tbody");
      tb.innerHTML = "";
      for (const t of cts.data) {
        const tr = document.createElement("tr");
        tr.innerHTML = `<td>${esc(t.name)}</td><td>${esc(t.connector)}</td>
          <td>${esc(t.table_type)}</td>
          <td class="sub">${t.schema_fields.map((f) => esc(f.name)).join(", ")}</td>
          <td></td>`;
        const del = el(`<a>delete</a>`);
        del.onclick = async () => {
          await api("DELETE", `/api/v1/connection_tables/${t.id}`); refresh();
        };
        tr.lastElementChild.appendChild(del);
        tb.appendChild(tr);
      }
      const cps = await api("GET", "/api/v1/connection_profiles");
      const pb = $("#cps tbody");
      pb.innerHTML = "";
      const profSel = $("#ct-profile");
      const curProf = profSel.value;
      profSel.innerHTML = '<option value="">no profile</option>' +
        cps.data.map((p) => `<option value="${esc(p.id)}"${p.id === curProf
          ? " selected" : ""}>${esc(p.name)}</option>`).join("");
      for (const p of cps.data) {
        const tr = document.createElement("tr");
        tr.innerHTML = `<td>${esc(p.name)}</td><td>${esc(p.connector)}</td><td></td>`;
        const del = el(`<a>delete</a>`);
        del.onclick = async () => {
          try { await api("DELETE", `/api/v1/connection_profiles/${p.id}`); refresh(); }
          catch (e) { alert(e.message); }
        };
        tr.lastElementChild.appendChild(del);
        pb.appendChild(tr);
      }
    } catch (e) { /* transient */ }
  }

  refresh();
  const timer = setInterval(refresh, 4000);
  return () => clearInterval(timer);
}
