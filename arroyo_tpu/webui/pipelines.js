// Pipeline editor + index (reference CreatePipeline.tsx / CodeEditor.tsx /
// PipelinesIndex.tsx): SQL with validation against /pipelines/validate,
// launch, list with per-pipeline jobs and delete.
import { api, el, esc } from "/webui/app.js";

export async function pipelinesView(mount) {
  mount.appendChild(el(`<div class="cols">
    <div>
      <div class="panel">
        <h2>New pipeline</h2>
        <textarea id="sql" spellcheck="false" placeholder="CREATE TABLE ...;
INSERT INTO ... SELECT ...;"></textarea>
        <div class="row">
          <button class="ghost" id="validate">Validate</button>
          <button id="start">Start</button>
          <input id="pname" placeholder="name" style="flex:1">
          <input id="par" type="number" min="1" value="1" style="width:64px"
                 title="parallelism">
        </div>
        <div id="vmsg" class="row"></div>
      </div>
    </div>
    <div>
      <div class="panel">
        <h2>Pipelines</h2>
        <table id="pls"><thead><tr>
          <th>name</th><th>parallelism</th><th>jobs</th><th></th>
        </tr></thead><tbody></tbody></table>
      </div>
    </div>
  </div>`));
  const $ = (s) => mount.querySelector(s);

  $("#validate").onclick = async () => {
    const m = $("#vmsg");
    try {
      const r = await api("POST", "/api/v1/pipelines/validate",
                          { query: $("#sql").value });
      m.innerHTML = r.valid ? '<span class="ok">valid</span>'
        : `<span class="err">${esc(r.errors.join("\n"))}</span>`;
    } catch (e) { m.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
  };
  $("#start").onclick = async () => {
    const m = $("#vmsg");
    try {
      const r = await api("POST", "/api/v1/pipelines", {
        query: $("#sql").value, name: $("#pname").value || "pipeline",
        parallelism: Number($("#par").value) || 1 });
      m.innerHTML = `<span class="ok">started ${esc(r.job_id)}</span>`;
      refresh();
    } catch (e) { m.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
  };

  async function refresh() {
    try {
      const pls = await api("GET", "/api/v1/pipelines");
      // one jobs fetch grouped client-side (not one per pipeline per poll)
      const allJobs = await api("GET", "/api/v1/jobs");
      const byPl = {};
      for (const j of allJobs.data)
        (byPl[j.pipeline_id] = byPl[j.pipeline_id] || []).push(j);
      const tb = $("#pls tbody");
      tb.innerHTML = "";
      for (const p of pls.data) {
        const states = (byPl[p.id] || []).map((j) =>
          `<span class="state ${esc(j.state)}">${esc(j.state)}</span>`).join(" ");
        const tr = document.createElement("tr");
        tr.innerHTML = `<td>${esc(p.name)}</td><td>${p.parallelism}</td>
          <td>${states || '<span class="sub">none</span>'}</td><td></td>`;
        const del = el(`<a>delete</a>`);
        del.onclick = async () => {
          try { await api("DELETE", `/api/v1/pipelines/${p.id}`); refresh(); }
          catch (e) { alert(e.message); }
        };
        tr.lastElementChild.appendChild(del);
        tb.appendChild(tr);
      }
    } catch (e) { /* transient */ }
  }

  refresh();
  const timer = setInterval(refresh, 3000);
  return () => clearInterval(timer);
}
