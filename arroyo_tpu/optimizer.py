"""Graph optimizers.

ChainingOptimizer equivalent (crates/arroyo-datastream/src/optimizers.rs:
40-105): fuse maximal runs of chainable operators connected by Forward edges
with equal parallelism and single fan-in/fan-out into one CHAINED node, so
each fused run executes as one task — no intermediate queues, threads, or
collector hops. Gated by ``pipeline.chaining.enabled``.
"""

from __future__ import annotations

from .graph import EdgeType, Graph, Node, OpName

# chainable single-input operators. The reference merges by graph shape
# alone; multi-input operators (joins) and sources are excluded here, and a
# keyed Shuffle edge is crossable only at parallelism 1 (where hashing to one
# destination is the identity routing and fusion is semantics-preserving).
CHAINABLE = {
    OpName.VALUE,
    OpName.KEY,
    OpName.WATERMARK,
    OpName.TUMBLING_AGGREGATE,
    OpName.SLIDING_AGGREGATE,
    OpName.SINK,
}


def _single_out(g: Graph, nid: str):
    es = g.out_edges(nid)
    return es[0] if len(es) == 1 else None


def _edge_fusable(g: Graph, e) -> bool:
    p_src = g.nodes[e.src].parallelism
    p_dst = g.nodes[e.dst].parallelism
    if p_src != p_dst:
        return False
    if e.edge_type == EdgeType.FORWARD:
        return True
    return e.edge_type == EdgeType.SHUFFLE and p_src == 1


def chain_graph(g: Graph) -> Graph:
    """Returns a new graph with chainable runs fused (input unmodified)."""
    consumed: set[str] = set()
    runs: list[list[str]] = []
    for node in g.topo_order():
        nid = node.node_id
        if nid in consumed or node.op not in CHAINABLE or node.op == OpName.SINK:
            continue
        if len(g.in_edges(nid)) != 1:
            continue
        run = [nid]
        cur = nid
        while True:
            e = _single_out(g, cur)
            if e is None or not _edge_fusable(g, e):
                break
            nxt = g.nodes[e.dst]
            if nxt.op not in CHAINABLE or len(g.in_edges(e.dst)) != 1:
                break
            run.append(e.dst)
            cur = e.dst
        if len(run) >= 2:
            runs.append(run)
            consumed.update(run)

    if not runs:
        return g

    rep: dict[str, str] = {}  # member node -> fused node id
    fused_cfg: dict[str, dict] = {}
    for run in runs:
        fid = "+".join(run)
        for nid in run:
            rep[nid] = fid
        members = [(g.nodes[nid].op.value, g.nodes[nid].config) for nid in run]
        fused_cfg[fid] = {"members": members}
        # plan-time compilability marking (engine/segment.py): the maximal
        # traceable prefix of the run, judged statically from op kinds and
        # expression shapes. The runtime still gates on real column dtypes
        # and verifies the first batch — this marking only says "worth
        # attempting", so an unmarked chain never pays a compile probe.
        # The marking's "mesh" field additionally says whether the prefix
        # is shard_map-fusable with a sharded window aggregate (no
        # in-trace filters past the hoistable head) — the runtime only
        # builds the fused per-shard program when it is True AND
        # device.mesh-devices > 1 picked a ShardedAggregator
        from .engine.segment import segment_marking, segment_reject_reason

        marking = segment_marking(members)
        if marking is not None:
            fused_cfg[fid]["compile"] = marking
        else:
            # explain WHY at plan time: `check` (AR009 INFO), `explain`,
            # `top`, and the executed-graph view all surface this string,
            # so an uncompiled segment stops being an unexplained runtime
            # event
            fused_cfg[fid]["compile_reject"] = segment_reject_reason(members)

    out = Graph()
    for nid, node in g.nodes.items():
        if nid in rep:
            fid = rep[nid]
            if fid not in out.nodes:
                out.add_node(Node(fid, OpName.CHAINED, fused_cfg[fid],
                                  node.parallelism, description="chained"))
        else:
            out.add_node(Node(nid, node.op, node.config, node.parallelism,
                              node.description))
    for e in g.edges:
        src = rep.get(e.src, e.src)
        dst = rep.get(e.dst, e.dst)
        if src == dst:
            continue  # internal chain edge
        out.add_edge(src, dst, e.edge_type, e.schema)
    return out
