"""Core dataflow types.

TPU-native re-design of the reference's core types
(reference: crates/arroyo-types/src/lib.rs — Watermark :162, ArrowMessage :168,
SignalMessage :174, CheckpointBarrier :481, TaskInfo :375, Window :14,
server_for_hash/range_for_server :621/:630, JoinType :354).

Timestamps are int64 microseconds since the unix epoch throughout (the reference
uses SystemTime with microsecond precision in its Arrow schemas).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

U64_MAX = (1 << 64) - 1

# Sentinel timestamp used for "idle" watermarks.
IDLE = None


@dataclass(frozen=True)
class Watermark:
    """Event-time watermark. ``value is None`` means the source is idle
    (reference: arroyo-types/src/lib.rs:162 Watermark::{EventTime, Idle})."""

    value: Optional[int]  # micros, or None for Idle

    @property
    def is_idle(self) -> bool:
        return self.value is None

    @staticmethod
    def event_time(micros: int) -> "Watermark":
        return Watermark(int(micros))

    @staticmethod
    def idle() -> "Watermark":
        return Watermark(None)


@dataclass(frozen=True)
class CheckpointBarrier:
    """Aligned checkpoint barrier flowing with the data
    (reference: arroyo-types/src/lib.rs:481)."""

    epoch: int
    min_epoch: int = 0
    timestamp: int = 0  # micros
    then_stop: bool = False


class SignalKind(enum.Enum):
    BARRIER = "barrier"
    WATERMARK = "watermark"
    STOP = "stop"
    END_OF_DATA = "end_of_data"


@dataclass(frozen=True)
class Signal:
    """In-band control message interleaved with data batches
    (reference: arroyo-types/src/lib.rs:174 SignalMessage)."""

    kind: SignalKind
    watermark: Optional[Watermark] = None
    barrier: Optional[CheckpointBarrier] = None

    @staticmethod
    def watermark_of(wm: Watermark) -> "Signal":
        return Signal(SignalKind.WATERMARK, watermark=wm)

    @staticmethod
    def barrier_of(b: CheckpointBarrier) -> "Signal":
        return Signal(SignalKind.BARRIER, barrier=b)

    @staticmethod
    def stop() -> "Signal":
        return Signal(SignalKind.STOP)

    @staticmethod
    def end_of_data() -> "Signal":
        return Signal(SignalKind.END_OF_DATA)


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"


@dataclass(frozen=True)
class Window:
    """Half-open event-time interval [start, end) in micros
    (reference: arroyo-types/src/lib.rs:14)."""

    start: int
    end: int

    def contains(self, ts: int) -> bool:
        return self.start <= ts < self.end


class SourceFinishType(enum.Enum):
    """How a source run() ended (reference: arroyo-operator/src/operator.rs)."""

    GRACEFUL = "graceful"  # emit EndOfData downstream, drain windows
    IMMEDIATE = "immediate"  # stop now (Stop signal)
    FINAL = "final"  # checkpoint-then-stop completed


@dataclass(frozen=True)
class TaskInfo:
    """Identity of one physical subtask
    (reference: arroyo-types/src/lib.rs:375)."""

    job_id: str
    node_id: str
    operator_name: str
    subtask_index: int
    parallelism: int

    @property
    def key_range(self) -> tuple[int, int]:
        return range_for_server(self.subtask_index, self.parallelism)

    @property
    def task_id(self) -> str:
        return f"{self.node_id}-{self.subtask_index}"


def range_for_server(i: int, n: int) -> tuple[int, int]:
    """Contiguous u64 hash range owned by subtask ``i`` of ``n``
    (reference: arroyo-types/src/lib.rs:630). Inclusive [start, end]."""
    if not 0 <= i < n:
        raise ValueError(f"subtask {i} out of range for parallelism {n}")
    size = (U64_MAX // n) + 1
    start = size * i
    end = U64_MAX if i == n - 1 else start + size - 1
    return (start, end)


def server_for_hash(h: int, n: int) -> int:
    """Which of ``n`` subtasks owns 64-bit hash ``h``
    (reference: arroyo-types/src/lib.rs:621)."""
    size = (U64_MAX // n) + 1
    return min(h // size, n - 1)


# ---------------------------------------------------------------------------
# Control plane messages (engine <-> tasks), reference arroyo-rpc/src/lib.rs:84/:133


@dataclass(frozen=True)
class ControlMessage:
    """Engine -> task control (reference: arroyo-rpc/src/lib.rs:84)."""

    kind: str  # "checkpoint" | "stop" | "commit" | "load_compacted" | "no_op"
    barrier: Optional[CheckpointBarrier] = None
    epoch: Optional[int] = None


@dataclass
class CheckpointEvent:
    checkpoint_epoch: int
    node_id: str
    subtask_index: int
    time_micros: int
    event_type: str  # "started_alignment" | "started_checkpointing" | "finished_sync"


@dataclass
class ControlResp:
    """Task -> engine status (reference: arroyo-rpc/src/lib.rs:133)."""

    kind: str  # task_started|task_finished|task_failed|checkpoint_event|checkpoint_completed|error
    node_id: str = ""
    subtask_index: int = 0
    error: Optional[str] = None
    checkpoint_event: Optional[CheckpointEvent] = None
    subtask_metadata: Optional[dict] = None  # checkpoint_completed payload
    epoch: int = 0
    # task_finished only: True when the task drained cleanly (graceful EOF /
    # checkpoint-then-stop) so its state is final/durable and may stand in
    # for epoch coverage; False for stop/abort exits, whose state is NOT
    # durable — counting those would let an epoch go "complete" while a
    # subtask's snapshot is missing (sources would then replay from zero)
    clean: bool = True
