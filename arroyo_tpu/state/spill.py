"""Tiered state: spill cold per-key state to object storage.

ROADMAP item 3. "Millions of users" means per-key operator state (updating
aggregates, join side stores, COUNT(DISTINCT) multiplicity maps) that cannot
stay resident in one subtask's RAM. This module adds the cold tier under
``state/tables.py``: the operator keeps its HOT working set in memory
exactly as before, and when the per-subtask budget
(``state.spill.budget-bytes``, measured with the same estimator that feeds
the ``arroyo_state_bytes`` gauges) is breached, the coldest hash-range
partitions — picked by a deterministic logical-clock LRU, never wall time —
are written as immutable parquet *runs* to the existing ``state/storage.py``
backend (local/S3/GCS plus the shared retry/circuit-breaker layer for free).

Every run carries a bloom filter and min/max zone maps over both the key
hash and the row event time, so a probe (``KeyedSpillAnnex.lookup_many``,
``RowSpillAnnex.probe``) touches only the files that can possibly hold the
key — the partition-wise spill + cheap probe pruning design of "Support
Aggregate Analytic Window Function over Large Data by Spilling"
(arXiv:2007.10385).

Ownership protocol (the correctness core):

  * a key's newest copy wins: the hot dict shadows every run, a newer run
    shadows older runs (runs are scanned newest-first).
  * promote-and-disown: the moment a probe promotes a key back into the hot
    tier, the annex tombstones it — the hot dict is now the single owner.
    Tombstones fold into the next spilled run as dead rows (shadowing stale
    copies) and are dropped entirely when a full-partition compaction
    proves no older copy remains.
  * spill is all-or-nothing: the run files land durably BEFORE the keys
    leave the hot dict. A storage failure mid-spill degrades — the
    partition is re-pinned hot, a ``SPILL_FALLBACK`` event is emitted, and
    spilling backs off — it never corrupts state or kills the job.
  * checkpoints reference runs by manifest (``checkpoint_manifest`` into a
    ``<table>__spill`` global table), never re-upload them; restore rebuilds
    the exact tiered layout (runs + tombstones + access clocks) so replay
    picks the same eviction victims the original run would have.

Run files live under ``{storage_url}/{job}/spill/operator-{node}/`` —
outside the per-epoch checkpoint dirs, because one immutable run is
typically referenced by MANY epochs. ``cleanup_spill_runs`` (driven by the
controller's checkpoint-GC tick) deletes a run only when no surviving
checkpoint references it AND its epoch tag proves it is not a fresh
post-checkpoint file.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Callable, Iterable, Optional

import numpy as np

from ..hashing import splitmix64
from ..metrics import Histogram
from . import storage
from .tables import read_columnar, write_columnar

_U64 = np.uint64(0xFFFFFFFFFFFFFFFF)
_BLOOM_SALT = np.uint64(0xA5A5A5A55A5A5A5A)

# files touched per probe: the zone-map/bloom effectiveness signal
# (0 = pruned everything; a growing tail means compaction is falling behind)
PROBE_FILES_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)

_RUN_NAME_RE = re.compile(r"^run-.+-s(\d+)-e(\d+)-(\d+)")


def _config():
    from ..config import config

    return config()


def spill_enabled() -> bool:
    return bool(_config().get("state.spill.enabled", False))


def spill_budget_bytes() -> int:
    return int(_config().get("state.spill.budget-bytes", 64 * 1024 * 1024))


def _u64(h: int) -> int:
    return h & 0xFFFFFFFFFFFFFFFF


def _i64(u: int) -> int:
    u = int(u)
    return u - (1 << 64) if u >= (1 << 63) else u


class SpillStats:
    """Per-operator spill counters (single writer: the task thread).
    Shared by the operator's annexes and read by ``TaskProfiler.refresh``
    into the ``arroyo_spill_*`` metric families."""

    __slots__ = ("bytes_total", "runs_written", "probes", "probe_files",
                 "compactions", "failures")

    def __init__(self):
        self.bytes_total = 0
        self.runs_written = 0
        self.probes = 0
        self.probe_files = Histogram(PROBE_FILES_BUCKETS)
        self.compactions = 0
        self.failures = 0


def merge_spill_stats(parts: list[Optional[dict]]) -> Optional[dict]:
    """Fold several ``spill_stats()`` dicts (e.g. a chain's members) into
    one: counters sum, the probe-files histograms merge bucket-wise."""
    parts = [p for p in parts if p]
    if not parts:
        return None
    hist = Histogram(PROBE_FILES_BUCKETS)
    out = {"bytes_total": 0, "hot": 0, "cold": 0, "probe_files": hist}
    for p in parts:
        out["bytes_total"] += int(p.get("bytes_total", 0))
        out["hot"] += int(p.get("hot", 0))
        out["cold"] += int(p.get("cold", 0))
        h = p.get("probe_files")
        if isinstance(h, Histogram) and tuple(h.buckets) == PROBE_FILES_BUCKETS:
            for i, c in enumerate(h.counts):
                hist.counts[i] += c
            hist.count += h.count
            hist.sum += h.sum
    return out


# ---------------------------------------------------------------- bloom


class BloomFilter:
    """Deterministic bloom filter over u64 key hashes (double hashing via
    two splitmix64 lanes; ~1% false positives at 10 bits/key, k=7)."""

    __slots__ = ("m", "k", "words")

    def __init__(self, m: int, k: int, words: np.ndarray):
        self.m = m
        self.k = k
        self.words = words

    @staticmethod
    def build(keys_u64: np.ndarray, bits_per_key: int = 10,
              k: int = 7) -> "BloomFilter":
        n = max(1, len(keys_u64))
        m = ((bits_per_key * n + 63) // 64) * 64
        words = np.zeros(m // 64, dtype=np.uint64)
        if len(keys_u64):
            keys_u64 = np.asarray(keys_u64, dtype=np.uint64)
            h1 = splitmix64(keys_u64)
            h2 = splitmix64(keys_u64 ^ _BLOOM_SALT)
            for i in range(k):
                idx = (h1 + np.uint64(i) * h2) % np.uint64(m)
                np.bitwise_or.at(
                    words, (idx >> np.uint64(6)).astype(np.int64),
                    np.uint64(1) << (idx & np.uint64(63)))
        return BloomFilter(m, k, words)

    def contains(self, keys_u64: np.ndarray) -> np.ndarray:
        """Boolean mask per key: True = possibly present."""
        keys_u64 = np.asarray(keys_u64, dtype=np.uint64)
        if not len(keys_u64):
            return np.zeros(0, dtype=bool)
        h1 = splitmix64(keys_u64)
        h2 = splitmix64(keys_u64 ^ _BLOOM_SALT)
        ok = np.ones(len(keys_u64), dtype=bool)
        for i in range(self.k):
            idx = (h1 + np.uint64(i) * h2) % np.uint64(self.m)
            bits = (self.words[(idx >> np.uint64(6)).astype(np.int64)]
                    >> (idx & np.uint64(63))) & np.uint64(1)
            ok &= bits != 0
        return ok

    def state(self) -> dict:
        return {"m": self.m, "k": self.k, "words": self.words.tobytes()}

    @staticmethod
    def from_state(d: dict) -> "BloomFilter":
        return BloomFilter(int(d["m"]), int(d["k"]),
                           np.frombuffer(d["words"], dtype=np.uint64).copy())


# ------------------------------------------------------------ run plumbing


def _zone_overlaps(meta: dict, lo: int, hi: int) -> bool:
    return not (meta["min_key"] > hi or meta["max_key"] < lo)


class _AnnexBase:
    """Shared plumbing of both annex flavors: run naming, fault-guarded
    file IO, the shared stats object, and the structured-event emitter."""

    def __init__(self, task_info, storage_url: str, table: str,
                 stats: Optional[SpillStats] = None):
        cfg = _config()
        self.task_info = task_info
        self.table = table
        self.dir = os.path.join(storage_url, task_info.job_id, "spill",
                                f"operator-{task_info.node_id}")
        self.key_lo, self.key_hi = task_info.key_range
        self.target_file_bytes = int(
            cfg.get("state.spill.target-file-bytes", 4 * 1024 * 1024))
        self.max_runs = max(2, int(cfg.get("state.spill.max-runs", 4)))
        self.stats = stats if stats is not None else SpillStats()
        self.epoch = 0  # last barrier epoch; tags run names for safe GC
        self.next_seq = 1
        # call-count backoffs after a failed write (deterministic, no
        # clocks): spill and compaction back off independently — memory
        # relief must not stall because a merge failed, and vice versa
        self._skip_spills = 0
        self._skip_compacts = 0
        self._made_dirs = False
        self._announced = False

    # -- events ------------------------------------------------------------

    def _emit(self, level: str, code: str, message: str, data: dict) -> None:
        from ..obs.events import recorder

        ti = self.task_info
        recorder.record(ti.job_id, level, code, message, node=ti.node_id,
                        subtask=ti.subtask_index, data=data)

    def _announce_spill(self, data: dict) -> None:
        if not self._announced:
            self._announced = True
            self._emit("INFO", "SPILL_STARTED",
                       f"state spilling engaged for table {self.table!r}",
                       data)

    def _degrade(self, what: str, err: Exception) -> None:
        self.stats.failures += 1
        self._emit("WARN", "SPILL_FALLBACK",
                   f"{what} failed for table {self.table!r}; state stays "
                   "resident and the failed path backs off",
                   {"table": self.table, "reason": str(err)[:200]})

    # -- file IO -----------------------------------------------------------

    def _run_name(self, seq: int) -> str:
        from .tables import _checkpoint_format

        ext = "parquet" if _checkpoint_format() == "parquet" else "npz"
        # the table name disambiguates annexes sharing one operator dir
        # (a join's left/right sides each keep their own seq counter)
        return (f"run-{self.table}-s{self.task_info.subtask_index:03d}"
                f"-e{self.epoch:07d}-{seq:06d}.{ext}")

    def _write_run(self, site: str, name: str, cols: dict) -> None:
        from ..faults import fault_point

        if not self._made_dirs:
            storage.makedirs(self.dir)
            self._made_dirs = True
        path = os.path.join(self.dir, name)
        fault_point(site, key=path, epoch=self.epoch,
                    subtask=self.task_info.subtask_index)
        # runs outlive the epoch whose manifest references them, so they
        # carry a self-describing integrity footer instead of a manifest
        # envelope (read_columnar strips + verifies it)
        write_columnar(path, cols, footer=True)

    def _read_run(self, meta: dict) -> dict:
        """Probe-path read: one in-place retry (an injected ``fail_once``
        or a transient blip the storage retry budget exhausted recovers
        here); a second failure propagates — the data exists only in this
        file, so the honest degradation is the task failing and the
        worker set restoring from the checkpoint, state intact."""
        from ..faults import fault_point

        path = os.path.join(self.dir, meta["file"])
        try:
            fault_point("spill_probe", key=path, epoch=self.epoch,
                        subtask=self.task_info.subtask_index)
            return read_columnar(path)
        except Exception:  # noqa: BLE001 - retried once, then propagates
            fault_point("spill_probe", key=path, epoch=self.epoch,
                        subtask=self.task_info.subtask_index)
            return read_columnar(path)

    def _bloom(self, meta: dict) -> BloomFilter:
        b = meta.get("__bloom_obj")
        if b is None:
            b = BloomFilter.from_state(meta["bloom"])
            meta["__bloom_obj"] = b
        return b


# ---------------------------------------------------------- keyed annex


class KeyedSpillAnnex(_AnnexBase):
    """Cold tier for keyed record state (one mutable record per key hash),
    the shape of ``UpdatingAggregate``'s accumulator map.

    The annex never holds the hot tier: the operator's own dict does. The
    annex owns the spilled runs, the per-partition tombstone sets, and the
    deterministic access clock that picks eviction victims. Values cross
    the boundary as ``pack()``-ed picklable payloads.
    """

    def __init__(self, task_info, storage_url: str, table: str,
                 stats: Optional[SpillStats] = None):
        super().__init__(task_info, storage_url, table, stats)
        pc = int(_config().get("state.spill.partition-count", 16))
        # partition-count is documented PER SUBTASK: subtasks own
        # contiguous top-bit slices of the hash space, so the global split
        # scales with parallelism to keep ~pc partitions inside each
        # subtask's range (otherwise high parallelism degenerates every
        # subtask to one victim and the clock LRU is vacuous). Powers of
        # two (>= 2: a 64-bit shift is undefined) so the partition is just
        # the hash's top bits; capped so run bookkeeping stays bounded.
        per_subtask = max(2, 1 << max(0, (pc - 1).bit_length()))
        par = max(1, 1 << max(0, (task_info.parallelism - 1).bit_length()))
        self.pc = min(1 << 16, per_subtask * par)
        self.shift = np.uint64(64 - self.pc.bit_length() + 1)
        self.runs: list[dict] = []  # oldest -> newest
        self.tombstones: dict[int, set[int]] = {}
        self.last_access: dict[int, int] = {}
        self.clock = 0

    # -- partitioning / clock ---------------------------------------------

    def partition_of(self, h: int) -> int:
        return int(np.uint64(_u64(h)) >> self.shift)

    def partitions_of(self, hashes: np.ndarray) -> np.ndarray:
        u = np.asarray(hashes).astype(np.int64).view(np.uint64)
        return (u >> self.shift).astype(np.int64)

    def touch(self, hashes: np.ndarray) -> None:
        """Advance the access clock for every partition the batch touched
        (one tick per call: replay-deterministic, no wall time)."""
        if not len(hashes):
            return
        self.clock += 1
        for p in np.unique(self.partitions_of(hashes)).tolist():
            self.last_access[p] = self.clock

    def has_runs(self) -> bool:
        return bool(self.runs)

    def local_partitions(self) -> int:
        """Partitions intersecting this subtask's key range (the
        denominator of the hot/cold gauge split)."""
        return (self.partition_of(self.key_hi)
                - self.partition_of(self.key_lo) + 1)

    def cold_partitions(self) -> int:
        return len({int(np.uint64(r["min_key"]) >> self.shift)
                    for r in self.runs})

    # -- probe -------------------------------------------------------------

    def lookup_many(self, hashes: Iterable[int]) -> dict[int, object]:
        """Resolve the newest spilled copy of each key and PROMOTE it: the
        returned keys are tombstoned (the caller's hot dict owns them now).
        Bloom + key zone maps prune the files touched; the histogram of
        files-per-probe is the pruning-effectiveness signal."""
        want = [h for h in hashes
                if h not in self.tombstones.get(self.partition_of(h), ())]
        self.stats.probes += 1
        if not want or not self.runs:
            self.stats.probe_files.observe(0)
            return {}
        found: dict[int, object] = {}
        files = 0
        pending = np.array(sorted(want), dtype=np.int64)
        for meta in reversed(self.runs):  # newest copy wins
            if not len(pending):
                break
            u = pending.view(np.uint64)
            lo, hi = int(u.min()), int(u.max())
            if not _zone_overlaps(meta, lo, hi):
                continue
            mask = self._bloom(meta).contains(u)
            if not mask.any():
                continue
            files += 1
            cols = self._read_run(meta)
            rk = np.asarray(cols["_key"], dtype=np.uint64).view(np.int64)
            hit = np.isin(pending[mask], rk)
            cand = pending[mask][hit]
            if len(cand):
                dead_col = np.asarray(cols["__dead"], dtype=bool)
                vals = cols["__val"]
                idx = {int(k): j for j, k in enumerate(rk.tolist())}
                for h in cand.tolist():
                    j = idx[h]
                    if not dead_col[j]:  # a dead row shadows older copies
                        found[h] = pickle.loads(vals[j])
                pending = pending[~np.isin(pending, cand)]
        self.stats.probe_files.observe(files)
        for h in found:
            self.tombstones.setdefault(self.partition_of(h), set()).add(h)
        return found

    def tombstone(self, h: int) -> None:
        """Disown a key explicitly (a hot key died while stale copies may
        remain in runs). Promote paths tombstone automatically."""
        if self.runs:
            self.tombstones.setdefault(self.partition_of(h), set()).add(h)

    # -- spill -------------------------------------------------------------

    def pick_victims(self, hot_counts: dict[int, int],
                     excess_entries: int) -> list[int]:
        """Coldest partitions first (logical-clock LRU, partition id as the
        deterministic tie-break) until ``excess_entries`` hot entries are
        covered."""
        order = sorted((p for p, c in hot_counts.items() if c),
                       key=lambda p: (self.last_access.get(p, 0), p))
        out, covered = [], 0
        for p in order:
            if covered >= excess_entries:
                break
            out.append(p)
            covered += hot_counts[p]
        return out

    def spill(self, partition: int, items: list[tuple[int, object]]) -> bool:
        """Write one partition's hot entries (plus its tombstones as dead
        rows) as new run file(s). All-or-nothing: runs register only after
        every chunk is durable; on failure nothing changed and the caller
        keeps the entries hot. Returns True when the caller may drop them."""
        if self._skip_spills > 0:
            self._skip_spills -= 1
            return False
        items = sorted(items, key=lambda kv: _u64(kv[0]))
        alive_keys = {h for h, _v in items}
        dead = sorted((self.tombstones.get(partition) or set()) - alive_keys,
                      key=_u64)
        if not items and not dead:
            return True
        rows: list[tuple[int, bytes, int, bool]] = []  # (h, payload, ts, dead)
        for h, v in items:
            payload = pickle.dumps(v, protocol=4)
            rows.append((h, payload, int(self._ts_of_packed(v)), False))
        rows.extend((h, b"", 0, True) for h in dead)
        rows.sort(key=lambda r: _u64(r[0]))
        chunks = self._chunk(rows)
        metas, written = [], 0
        try:
            for chunk in chunks:
                meta = self._encode_and_write("spill_write", chunk)
                metas.append(meta)
                written += meta["bytes"]
        except Exception as e:  # noqa: BLE001 - storage exhausted retries
            # unregistered chunk files are orphans cleanup_spill_runs owns
            self._degrade("spill write", e)
            self._skip_spills = 16
            return False
        self.runs.extend(metas)
        self.stats.bytes_total += written
        self.stats.runs_written += len(metas)
        self.tombstones.pop(partition, None)
        self._announce_spill({"table": self.table, "partition": partition,
                              "rows": len(items), "bytes": written})
        self._maybe_compact(partition)
        return True

    def _ts_of_packed(self, packed) -> int:
        # packed payloads carry their event time at index -1 by the
        # operator pack contract; tolerate anything else with ts=0
        try:
            return int(packed[-1])
        except Exception:  # noqa: BLE001
            return 0

    def _chunk(self, rows: list) -> list[list]:
        out, cur, size = [], [], 0
        for r in rows:
            cur.append(r)
            size += len(r[1]) + 32
            if size >= self.target_file_bytes:
                out.append(cur)
                cur, size = [], 0
        if cur:
            out.append(cur)
        return out

    def _encode_and_write(self, site: str, rows: list) -> dict:
        keys = np.array([_u64(h) for h, _p, _t, _d in rows], dtype=np.uint64)
        ts = np.array([t for _h, _p, t, _d in rows], dtype=np.int64)
        dead = np.array([d for _h, _p, _t, d in rows], dtype=bool)
        vals = np.empty(len(rows), dtype=object)
        for j, (_h, p, _t, _d) in enumerate(rows):
            vals[j] = p
        name = self._run_name(self.next_seq)
        self._write_run(site, name, {
            "_key": keys, "__ts": ts, "__dead": dead, "__val": vals})
        self.next_seq += 1
        nbytes = int(sum(len(p) + 32 for _h, p, _t, _d in rows))
        alive_ts = ts[~dead]
        return {
            "file": name, "seq": self.next_seq - 1,
            "writer": self.task_info.subtask_index, "epoch": self.epoch,
            "gen": 0, "rows": int((~dead).sum()), "bytes": nbytes,
            "min_key": int(keys.min()), "max_key": int(keys.max()),
            "min_ts": int(alive_ts.min()) if len(alive_ts) else 0,
            "max_ts": int(alive_ts.max()) if len(alive_ts) else 0,
            "alive_min_ts": int(alive_ts.min()) if len(alive_ts) else None,
            "bloom": BloomFilter.build(keys).state(),
        }

    # -- compaction --------------------------------------------------------

    def _partition_span(self, partition: int) -> tuple[int, int]:
        width = 2 ** 64 // self.pc
        return partition * width, (partition + 1) * width - 1

    def _maybe_compact(self, partition: int) -> None:
        if self._skip_compacts > 0:
            self._skip_compacts -= 1
            return
        lo, hi = self._partition_span(partition)
        group = [r for r in self.runs
                 if r["min_key"] >= lo and r["max_key"] <= hi]
        if len(group) <= self.max_runs:
            return
        self.compact_partition(partition)

    def compact_partition(self, partition: int) -> bool:
        """Merge every run contained in one partition's key span into a
        single newest-wins generation: dead keys normally fold out
        entirely (every copy is inside the merge set); when a legacy run
        OUTSIDE the merge set still overlaps this span (a
        partition-count change across restores), dead markers are carried
        so they keep shadowing those older copies. Rows outside this
        subtask's key range drop (a rescale peer referencing the old
        files keeps them alive until GC). Old files are left for
        ``cleanup_spill_runs`` — older epochs' manifests still reference
        them."""
        lo, hi = self._partition_span(partition)
        group = [r for r in self.runs
                 if r["min_key"] >= lo and r["max_key"] <= hi]
        if len(group) < 2:
            return False
        group_ids = {id(r) for r in group}
        keep_dead = any(id(r) not in group_ids and _zone_overlaps(r, lo, hi)
                        for r in self.runs)
        best: dict[int, tuple[bytes, int, bool]] = {}
        seen: set[int] = set()
        gen = max(int(r.get("gen", 0)) for r in group) + 1
        try:
            # group preserves self.runs order, so reversed(group) is the
            # newest-first merge order
            for meta in reversed(group):
                cols = self._read_run(meta)
                rk = np.asarray(cols["_key"], dtype=np.uint64)
                dead_col = np.asarray(cols["__dead"], dtype=bool)
                ts = np.asarray(cols["__ts"], dtype=np.int64)
                vals = cols["__val"]
                in_range = (rk >= np.uint64(self.key_lo)) & \
                    (rk <= np.uint64(self.key_hi))
                for j in np.flatnonzero(in_range).tolist():
                    h = _i64(rk[j])
                    if h in seen:
                        continue
                    seen.add(h)
                    if not dead_col[j]:
                        best[h] = (vals[j], int(ts[j]), False)
                    elif keep_dead:
                        best[h] = (b"", 0, True)
            rows = [(h, p, t, d)
                    for h, (p, t, d) in sorted(best.items(),
                                               key=lambda kv: _u64(kv[0]))]
            metas = []
            for chunk in self._chunk(rows) if rows else []:
                m = self._encode_and_write("spill_compact", chunk)
                m["gen"] = gen
                metas.append(m)
        except Exception as e:  # noqa: BLE001 - keep the old runs: correct,
            # just more read amplification until the next attempt succeeds
            self._degrade("spill compaction", e)
            self._skip_compacts = 16
            return False
        self.runs = [r for r in self.runs if id(r) not in group_ids] + metas
        self.stats.compactions += 1
        return True

    # -- expiry ------------------------------------------------------------

    def scan_expired(self, cutoff: int,
                     exclude: Iterable[int]) -> list[tuple[int, object]]:
        """Every cold key whose NEWEST copy has ts < cutoff, promoted
        (tombstoned) so the caller can evict it exactly like a hot key.
        Zone-map gated: no file is read until the watermark actually
        passes the oldest surviving spilled row."""
        if not self.runs:
            return []
        alive_floor = min(
            (r["alive_min_ts"] for r in self.runs
             if r.get("alive_min_ts") is not None and r["rows"]),
            default=None)
        if alive_floor is None or alive_floor >= cutoff:
            return []
        exclude = set(exclude)
        seen: set[int] = set()
        expired: list[tuple[int, object]] = []
        for meta in reversed(self.runs):  # newest copy decides liveness
            # rows==0 runs (pure dead markers — a tombstone-only spill or a
            # chunk split that isolated the trailing dead rows) MUST still
            # be read: their markers shadow older alive copies, exactly
            # like they do on the lookup path
            cols = self._read_run(meta)
            rk = np.asarray(cols["_key"], dtype=np.uint64)
            dead_col = np.asarray(cols["__dead"], dtype=bool)
            ts = np.asarray(cols["__ts"], dtype=np.int64)
            vals = cols["__val"]
            in_range = (rk >= np.uint64(self.key_lo)) & \
                (rk <= np.uint64(self.key_hi))
            surviving_ts = []
            for j in np.flatnonzero(in_range).tolist():
                h = _i64(rk[j])
                if h in seen:
                    continue
                seen.add(h)
                if dead_col[j] or h in exclude or \
                        h in self.tombstones.get(self.partition_of(h), ()):
                    continue
                if int(ts[j]) < cutoff:
                    expired.append((h, pickle.loads(vals[j])))
                else:
                    surviving_ts.append(int(ts[j]))
            meta["alive_min_ts"] = min(surviving_ts) if surviving_ts else None
        expired.sort(key=lambda kv: _u64(kv[0]))
        for h, _v in expired:
            self.tombstones.setdefault(self.partition_of(h), set()).add(h)
        return expired

    # -- checkpoint / restore ---------------------------------------------

    def manifest(self) -> dict:
        return {
            "v": 1, "kind": "keyed", "pc": self.pc,
            "writer": self.task_info.subtask_index,
            "parallelism": self.task_info.parallelism,
            "clock": self.clock, "next_seq": self.next_seq,
            "last_access": dict(self.last_access),
            "tombstones": {p: sorted(s, key=_u64)
                           for p, s in self.tombstones.items() if s},
            "runs": [{k: v for k, v in r.items() if k != "__bloom_obj"}
                     for r in self.runs],
        }

    def adopt(self, manifests: list[dict]) -> None:
        """Rebuild the cold tier from checkpointed manifest(s): own entry
        on a plain restore, every overlapping peer entry on a rescale.
        Runs are adopted when their key zone overlaps our range; tombstone
        sets union (disjoint key ranges make that exact); clocks take the
        max so post-restore eviction picks the same victims."""
        by_file: dict[str, dict] = {}
        order: list[tuple[tuple, str]] = []
        for m in manifests:
            if not m or m.get("kind") != "keyed":
                continue
            self.clock = max(self.clock, int(m.get("clock", 0)))
            for p, c in (m.get("last_access") or {}).items():
                p = int(p)
                self.last_access[p] = max(self.last_access.get(p, 0), int(c))
            for p, ks in (m.get("tombstones") or {}).items():
                self.tombstones.setdefault(int(p), set()).update(ks)
            for r in m.get("runs") or ():
                if not _zone_overlaps(r, self.key_lo, self.key_hi):
                    continue
                if r["file"] not in by_file:
                    by_file[r["file"]] = dict(r)
                    order.append(((int(r.get("writer", 0)),
                                   int(r.get("seq", 0))), r["file"]))
            if int(m.get("writer", -1)) == self.task_info.subtask_index:
                self.next_seq = max(self.next_seq, int(m.get("next_seq", 1)))
        order.sort()
        self.runs = [by_file[f] for _k, f in order]
        for r in self.runs:
            if int(r.get("writer", -1)) == self.task_info.subtask_index:
                self.next_seq = max(self.next_seq, int(r.get("seq", 0)) + 1)


# ------------------------------------------------------------- row annex


class RowSpillAnnex(_AnnexBase):
    """Cold tier for multiset row state (many rows per key, each row a
    mutable (match_count, null_emitted, values...) record) — the shape of
    ``JoinWithExpiration``'s side stores.

    Runs are immutable; a probed row PROMOTES back into the live store and
    its file slot joins the run's dead-row set (persisted in the manifest,
    the file itself is never rewritten). Expiry marks rows dead in place
    and drops a run once nothing in it is alive."""

    def __init__(self, task_info, storage_url: str, table: str, n_vals: int,
                 stats: Optional[SpillStats] = None):
        super().__init__(task_info, storage_url, table, stats)
        self.n_vals = n_vals
        self.runs: list[dict] = []  # each meta carries "dead": set[int]

    def has_runs(self) -> bool:
        return bool(self.runs)

    def alive_rows(self) -> int:
        return sum(max(0, int(r["rows"]) - len(r["dead"])) for r in self.runs)

    def oldest_ts(self) -> Optional[int]:
        floors = [r["alive_min_ts"] for r in self.runs
                  if r.get("alive_min_ts") is not None]
        return min(floors) if floors else None

    def spill_rows(self, keys: np.ndarray, ts: np.ndarray,
                   match_count: np.ndarray, null_emitted: np.ndarray,
                   vals: list[np.ndarray]) -> bool:
        """Write the given live rows as run file(s); True when durable (the
        caller then kills them from the live store), False to keep them
        resident (backoff or a degraded write)."""
        if self._skip_spills > 0:
            self._skip_spills -= 1
            return False
        if not len(keys):
            return True
        order = np.lexsort((np.arange(len(keys)),
                            keys.astype(np.int64).view(np.uint64)))
        keys_u = keys.astype(np.int64).view(np.uint64)[order]
        ts_s = np.asarray(ts, dtype=np.int64)[order]
        mc_s = np.asarray(match_count, dtype=np.int64)[order]
        ne_s = np.asarray(null_emitted, dtype=bool)[order]
        vals_s = [np.asarray(v, dtype=object)[order] for v in vals]
        # chunk by the per-row floor estimate the state gauges use
        per_row = 8 * (3 + self.n_vals) + 2 + 64
        rows_per_file = max(1, self.target_file_bytes // per_row)
        metas, written = [], 0
        try:
            for lo in range(0, len(keys_u), rows_per_file):
                hi = min(len(keys_u), lo + rows_per_file)
                name = self._run_name(self.next_seq)
                cols = {"_key": keys_u[lo:hi], "__ts": ts_s[lo:hi],
                        "__mc": mc_s[lo:hi], "__ne": ne_s[lo:hi]}
                for i, v in enumerate(vals_s):
                    cols[f"__v{i}"] = v[lo:hi]
                self._write_run("spill_write", name, cols)
                self.next_seq += 1
                nbytes = (hi - lo) * per_row
                metas.append({
                    "file": name, "seq": self.next_seq - 1,
                    "writer": self.task_info.subtask_index,
                    "epoch": self.epoch, "gen": 0, "rows": hi - lo,
                    "bytes": nbytes,
                    "min_key": int(keys_u[lo:hi].min()),
                    "max_key": int(keys_u[lo:hi].max()),
                    "min_ts": int(ts_s[lo:hi].min()),
                    "max_ts": int(ts_s[lo:hi].max()),
                    "alive_min_ts": int(ts_s[lo:hi].min()),
                    "bloom": BloomFilter.build(keys_u[lo:hi]).state(),
                    "dead": set(),
                })
                written += nbytes
        except Exception as e:  # noqa: BLE001
            self._degrade("spill write", e)
            self._skip_spills = 16
            return False
        self.runs.extend(metas)
        self.stats.bytes_total += written
        self.stats.runs_written += len(metas)
        self._announce_spill({"table": self.table, "rows": int(len(keys_u)),
                              "bytes": written})
        return True

    def probe(self, keys: np.ndarray) -> Optional[tuple]:
        """Promote every alive spilled row whose key appears in ``keys``:
        returns (keys, ts, match_count, null_emitted, vals...) arrays for
        the caller to append into its live store (slots marked dead here).
        None when nothing matched."""
        self.stats.probes += 1
        if not self.runs or not len(keys):
            self.stats.probe_files.observe(0)
            return None
        want = np.unique(np.asarray(keys, dtype=np.int64).view(np.uint64))
        lo, hi = int(want.min()), int(want.max())
        out_k, out_t, out_m, out_n = [], [], [], []
        out_v: list[list] = [[] for _ in range(self.n_vals)]
        files = 0
        drop: list[dict] = []
        for meta in self.runs:
            if len(meta["dead"]) >= meta["rows"]:
                continue
            if not _zone_overlaps(meta, lo, hi):
                continue
            if not self._bloom(meta).contains(want).any():
                continue
            files += 1
            cols = self._read_run(meta)
            rk = np.asarray(cols["_key"], dtype=np.uint64)
            alive = np.ones(len(rk), dtype=bool)
            if meta["dead"]:
                alive[sorted(meta["dead"])] = False
            m = alive & np.isin(rk, want) & \
                (rk >= np.uint64(self.key_lo)) & (rk <= np.uint64(self.key_hi))
            idx = np.flatnonzero(m)
            if not len(idx):
                continue
            out_k.append(rk[idx].view(np.int64))
            out_t.append(np.asarray(cols["__ts"], dtype=np.int64)[idx])
            out_m.append(np.asarray(cols["__mc"], dtype=np.int64)[idx])
            out_n.append(np.asarray(cols["__ne"], dtype=bool)[idx])
            for i in range(self.n_vals):
                out_v[i].append(np.asarray(cols[f"__v{i}"],
                                           dtype=object)[idx])
            meta["dead"].update(idx.tolist())
            self._refresh_floor(meta, cols)
            if len(meta["dead"]) >= meta["rows"]:
                drop.append(meta)
        self.stats.probe_files.observe(files)
        for meta in drop:
            self.runs.remove(meta)
        if not out_k:
            return None
        return (np.concatenate(out_k), np.concatenate(out_t),
                np.concatenate(out_m), np.concatenate(out_n),
                [np.concatenate(v) for v in out_v])

    def _refresh_floor(self, meta: dict, cols: dict) -> None:
        ts = np.asarray(cols["__ts"], dtype=np.int64)
        rk = np.asarray(cols["_key"], dtype=np.uint64)
        alive = np.ones(len(ts), dtype=bool)
        if meta["dead"]:
            alive[sorted(meta["dead"])] = False
        alive &= (rk >= np.uint64(self.key_lo)) & \
            (rk <= np.uint64(self.key_hi))
        meta["alive_min_ts"] = int(ts[alive].min()) if alive.any() else None

    def expire(self, cutoff: int) -> int:
        """Kill every alive spilled row older than the retention cutoff;
        returns the count (the caller's expired/late accounting). Whole
        runs below the cutoff drop without a read when their row count is
        exact; straddling runs are read and marked."""
        dropped = 0
        keep: list[dict] = []
        for meta in self.runs:
            floor = meta.get("alive_min_ts")
            if floor is None or floor >= cutoff:
                keep.append(meta)
                continue
            if meta["max_ts"] < cutoff and not meta["dead"] and \
                    self.key_lo == 0 and self.key_hi == int(_U64):
                dropped += meta["rows"]  # whole run, sole owner: no read
                continue
            cols = self._read_run(meta)
            ts = np.asarray(cols["__ts"], dtype=np.int64)
            rk = np.asarray(cols["_key"], dtype=np.uint64)
            alive = np.ones(len(ts), dtype=bool)
            if meta["dead"]:
                alive[sorted(meta["dead"])] = False
            alive &= (rk >= np.uint64(self.key_lo)) & \
                (rk <= np.uint64(self.key_hi))
            hit = alive & (ts < cutoff)
            dropped += int(hit.sum())
            meta["dead"].update(np.flatnonzero(hit).tolist())
            self._refresh_floor(meta, cols)
            if len(meta["dead"]) < meta["rows"]:
                keep.append(meta)
        self.runs = keep
        return dropped

    # -- checkpoint / restore ---------------------------------------------

    def manifest(self) -> dict:
        runs = []
        for r in self.runs:
            m = {k: v for k, v in r.items() if k not in ("dead", "__bloom_obj")}
            m["dead"] = sorted(r["dead"])
            runs.append(m)
        return {"v": 1, "kind": "rows", "writer": self.task_info.subtask_index,
                "parallelism": self.task_info.parallelism,
                "next_seq": self.next_seq, "runs": runs}

    def adopt(self, manifests: list[dict]) -> None:
        by_file: dict[str, dict] = {}
        order: list[tuple[tuple, str]] = []
        for m in manifests:
            if not m or m.get("kind") != "rows":
                continue
            for r in m.get("runs") or ():
                if not _zone_overlaps(r, self.key_lo, self.key_hi):
                    continue
                if r["file"] in by_file:
                    by_file[r["file"]]["dead"].update(r.get("dead") or ())
                else:
                    meta = dict(r)
                    meta["dead"] = set(r.get("dead") or ())
                    by_file[r["file"]] = meta
                    order.append(((int(r.get("writer", 0)),
                                   int(r.get("seq", 0))), r["file"]))
            if int(m.get("writer", -1)) == self.task_info.subtask_index:
                self.next_seq = max(self.next_seq, int(m.get("next_seq", 1)))
        order.sort()
        self.runs = [by_file[f] for _k, f in order]
        shared = len(manifests) > 1
        for r in self.runs:
            if shared:
                # a rescale may share one run between subtasks, and the
                # persisted floor was computed under the OLD owner's key
                # range (rows alive in OUR slice may sit below it, or the
                # old owner's slice may be fully dead with ours alive) —
                # reset to the run's global min_ts, the conservative bound;
                # the first probe/expire read recomputes the exact
                # per-range floor
                r["alive_min_ts"] = r["min_ts"]
            if int(r.get("writer", -1)) == self.task_info.subtask_index:
                self.next_seq = max(self.next_seq, int(r.get("seq", 0)) + 1)


# --------------------------------------------- manifest table convention


def checkpoint_manifest(ctx, table: str, annex) -> None:
    """Persist an annex's manifest into its ``<base>__spill`` global table
    (one entry per subtask, like ``persist_mark``). Spilled runs are
    referenced by name, never re-uploaded — ``TableManager.checkpoint``
    lifts the run list into the file metadata so checkpoint GC can see
    which run files are still live. The ``__spill`` suffix is a hard
    convention: the state auditor (LR203) and the GC both key on it."""
    ctx.table_manager.global_keyed(table).insert(
        ctx.task_info.subtask_index, annex.manifest())


def require_spill_for_manifest(ctx, table: str) -> None:
    """Guard for operators restoring WITHOUT spilling enabled: if the
    checkpoint's ``<base>__spill`` manifest still references runs, most of
    the keyspace lives in files only the annex can read — restoring hot
    rows alone would silently re-aggregate those keys from identity.
    Failing the restore is the honest outcome; re-enable
    ``state.spill.enabled`` (or compact the state back resident first)."""
    # endswith: a chained member's tables restore under a "c{i}." prefix
    for name, tbl in ctx.table_manager.globals.items():
        if not name.endswith(table):
            continue
        runs = manifest_run_files(tbl.data)
        if runs:
            raise RuntimeError(
                f"checkpoint manifest {name!r} references {len(runs)} "
                "spilled run file(s) but state.spill.enabled is false: "
                "restoring only the hot rows would silently discard the "
                "cold keyspace — re-enable state.spill.enabled to restore "
                "this job")


def restore_manifest(ctx, table: str) -> list[dict]:
    """Manifest entries for an annex restore: our OWN subtask's entry when
    the snapshot was taken at our parallelism (same key range, exact
    restore); EVERY peer entry on a rescale — a new subtask's range can
    straddle several old subtasks' manifests, and the adopting annex
    filters runs by key-range overlap."""
    ti = ctx.task_info
    tbl = ctx.table_manager.global_keyed(table)
    own = tbl.get(ti.subtask_index)
    if isinstance(own, dict) and \
            int(own.get("parallelism", -1)) == ti.parallelism:
        return [own]
    return [v for _k, v in sorted(tbl.items()) if v is not None]


def manifest_run_files(table_data: dict) -> list[str]:
    """Run file names referenced by a ``__spill`` table's manifest entries
    (checkpoint metadata + GC)."""
    out = set()
    for m in table_data.values():
        if isinstance(m, dict):
            for r in m.get("runs") or ():
                if isinstance(r, dict) and r.get("file"):
                    out.add(r["file"])
    return sorted(out)


# ------------------------------------------------------------------- GC


def cleanup_spill_runs(storage_url: str, job_id: str,
                       newest_complete_epoch: int) -> int:
    """Delete spill run files no surviving checkpoint references. Runs
    created at-or-after the newest complete epoch are always kept: they may
    be fresh post-checkpoint files the next manifest will reference (their
    epoch tag is embedded in the file name). Returns files removed."""
    base = os.path.join(storage_url, job_id, "spill")
    if not storage.isdir(base):
        return 0
    referenced: set[tuple[str, str]] = set()
    ckpt_base = os.path.join(storage_url, job_id, "checkpoints")
    if storage.isdir(ckpt_base):
        for cp in storage.listdir(ckpt_base):
            cdir = os.path.join(ckpt_base, cp)
            if not cp.startswith("checkpoint-") or not storage.isdir(cdir):
                continue
            for opd in storage.listdir(cdir):
                if not opd.startswith("operator-"):
                    continue
                for fn in storage.listdir(os.path.join(cdir, opd)):
                    if not (fn.startswith("metadata-") and
                            fn.endswith(".json")):
                        continue
                    import json as _json

                    try:
                        meta = _json.loads(storage.read_text(
                            os.path.join(cdir, opd, fn)))
                    except Exception:  # noqa: BLE001 - torn metadata: skip
                        continue
                    for fm in meta.get("files", ()):
                        for rf in fm.get("spill_runs", ()):
                            referenced.add((opd, rf))
    removed = 0
    for opd in storage.listdir(base):
        d = os.path.join(base, opd)
        if not opd.startswith("operator-") or not storage.isdir(d):
            continue
        for fn in storage.listdir(d):
            m = _RUN_NAME_RE.match(fn)
            if m is None:
                continue
            if int(m.group(2)) >= newest_complete_epoch:
                continue
            if (opd, fn) in referenced:
                continue
            try:
                storage.remove(os.path.join(d, fn))
                removed += 1
            except FileNotFoundError:
                pass
    return removed
