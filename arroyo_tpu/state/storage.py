"""URL-dispatched object storage behind checkpoints and file connectors.

Equivalent of crates/arroyo-storage (StorageProvider, lib.rs:33 /
BackendConfig, lib.rs:180-340): one path-string API that reads/writes
local filesystems or object stores depending on the URL scheme —
``/abs/path`` or ``file://`` for local, ``s3://bucket/prefix`` for
S3-compatible storage (boto3 when available; tests inject a fake client
via ``set_s3_client``), ``gs://bucket/prefix`` for Google Cloud Storage
(from-scratch JSON-API client over urllib; tests inject via
``set_gcs_client``). Directory-shaped operations (listdir/isdir/rmtree)
are emulated with delimiter listings, mirroring how the reference treats
checkpoint paths as key prefixes.

All writes are atomic-publish: local files go through tmp + os.replace,
object-store puts are atomic by the services' semantics. S3 writes above
``storage.multipart-threshold-bytes`` (default 8 MiB) go through the
multipart API (lib.rs:317 analog) with abort-on-error.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Callable, Optional

from ..faults import fault_point
from ..utils.retry import CircuitBreaker, RetryPolicy, default_transient, retry_call

_log = logging.getLogger("arroyo_tpu.storage")

_s3_client = None
_gcs_client = None


class IntegrityError(RuntimeError):
    """A state artifact's bytes do not match its recorded checksum
    envelope — truncated upload, bit rot, or a torn write. Restore paths
    catch this to quarantine the epoch and fall back; it is NOT a
    transient storage fault and must never be retried."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"integrity check failed for {path}: {reason}")
        self.path = path
        self.reason = reason


def _crc_impl():
    """(crc function, algo name): hardware crc32c when a library provides
    it, else stdlib zlib.crc32. The algo NAME is recorded in every
    envelope so a reader recomputes with the writer's algorithm."""
    global _crc_fn, _crc_algo
    if _crc_fn is None:
        try:
            from crc32c import crc32c as _c  # type: ignore

            _crc_fn, _crc_algo = _c, "crc32c"
        except ImportError:
            import zlib

            _crc_fn, _crc_algo = zlib.crc32, "crc32"
    return _crc_fn, _crc_algo


_crc_fn = None
_crc_algo = None


def checksum_of(data: bytes) -> dict:
    """Integrity envelope for one artifact: {crc, len, algo}."""
    fn, algo = _crc_impl()
    return {"crc": fn(data) & 0xFFFFFFFF, "len": len(data), "algo": algo}


def verify_envelope(data: bytes, env: dict, path: str) -> None:
    """Raise IntegrityError unless ``data`` matches the recorded envelope.
    An envelope recorded with an algo this host cannot compute degrades to
    the length check (logged once per call, never silently)."""
    want_len = env.get("len")
    if want_len is not None and len(data) != int(want_len):
        raise IntegrityError(
            path, f"length {len(data)} != recorded {want_len}")
    algo = env.get("algo")
    fn, have = _crc_impl()
    if algo not in (None, have):
        if algo == "crc32":
            import zlib

            fn = zlib.crc32
        else:
            _log.warning("cannot verify %s: recorded algo %r unavailable "
                         "(length check only)", path, algo)
            return
    if "crc" in env and (fn(data) & 0xFFFFFFFF) != int(env["crc"]):
        raise IntegrityError(
            path, f"{algo or have} mismatch (recorded {env['crc']})")


# Self-describing trailer for artifacts that outlive the epoch whose
# manifest would otherwise carry their envelope (spill runs): payload +
# [crc u32][len u64][algo 8s][magic 8s]. The magic sits at the very end so
# a reader can detect the footer from the tail alone.
FOOTER_MAGIC = b"ARROYOCK"
_FOOTER_LEN = 4 + 8 + 8 + 8


def wrap_footer(data: bytes) -> bytes:
    import struct

    env = checksum_of(data)
    return data + struct.pack(
        ">IQ", env["crc"], env["len"]) + env["algo"].encode().ljust(8) \
        + FOOTER_MAGIC


def unwrap_footer(data: bytes, path: str = "<buffer>",
                  verify: bool = True) -> bytes:
    """Strip (and optionally verify) the integrity footer. Data without a
    footer passes through untouched — pre-upgrade runs stay readable."""
    import struct

    if len(data) < _FOOTER_LEN or not data.endswith(FOOTER_MAGIC):
        return data
    trailer = data[-_FOOTER_LEN:]
    crc, length = struct.unpack(">IQ", trailer[:12])
    algo = trailer[12:20].strip().decode("ascii", "replace")
    payload = data[:-_FOOTER_LEN]
    if verify:
        verify_envelope(payload, {"crc": crc, "len": length, "algo": algo},
                        path)
    return payload


def _apply_corruption(data: bytes, mode: str) -> bytes:
    """Deterministic chaos corruption (``storage.*:corrupt=<mode>``):
    bitflip flips one bit of the middle byte; truncate keeps the first
    half. Both are detectable by any crc+length envelope."""
    if not data:
        return data
    if mode == "truncate":
        return data[:len(data) // 2]
    mid = len(data) // 2
    return data[:mid] + bytes([data[mid] ^ 0x01]) + data[mid + 1:]

MULTIPART_DEFAULT = 8 * 1024 * 1024

# One breaker across all object-store ops: when the store is hard-down,
# checkpoint attempts fail fast instead of each burning a full retry
# schedule (the controller's restart budget then governs what happens).
_breaker = CircuitBreaker(threshold=8, cooldown_s=5.0, name="storage")


def _policy() -> RetryPolicy:
    return RetryPolicy.from_config("storage.retry")


def reset_retry_state() -> None:
    """Close the storage circuit (tests isolate retry state per test)."""
    _breaker.reset()


def _guarded(site: str, key: str, fn: Callable):
    """Run one storage operation behind the shared retry layer, with the
    fault point INSIDE the retried callable so injected transient faults
    recover in place (no job restart)."""

    def _once():
        fault_point(site, key=key)
        return fn()

    return retry_call(_once, policy=_policy(), retry_on=default_transient,
                      description=f"{site} {key}", breaker=_breaker)


def _guarded_v(site: str, key: str, fn: Callable):
    """Like _guarded, but the callable receives the fault-point verdict —
    the data paths (get/put) apply non-raising ``corrupt`` verdicts to the
    bytes in flight, modeling bit rot / truncated uploads."""

    def _once():
        return fn(fault_point(site, key=key))

    return retry_call(_once, policy=_policy(), retry_on=default_transient,
                      description=f"{site} {key}", breaker=_breaker)


def set_s3_client(client) -> None:
    """Inject an S3 client (tests: an in-memory fake; production may pass a
    configured boto3 client to control credentials/endpoints)."""
    global _s3_client
    _s3_client = client


def set_gcs_client(client) -> None:
    """Inject a GCS client with the GcsHttpClient surface (download/upload/
    list/delete/exists); tests pass an in-memory fake."""
    global _gcs_client
    _gcs_client = client


def _get_s3():
    global _s3_client
    if _s3_client is None:
        try:
            import boto3  # type: ignore

            _s3_client = boto3.client("s3")
        except ImportError as e:
            raise RuntimeError(
                "s3:// storage requires boto3 (not installed) or an injected "
                "client via arroyo_tpu.state.storage.set_s3_client"
            ) from e
    return _s3_client


def _get_gcs():
    global _gcs_client
    if _gcs_client is None:
        _gcs_client = GcsHttpClient()
    return _gcs_client


class GcsHttpClient:
    """Minimal GCS JSON-API client over urllib (reference GCS backend,
    arroyo-storage lib.rs:192). Auth: bearer token from
    GOOGLE_OAUTH_ACCESS_TOKEN or the GCE metadata server; anonymous
    otherwise (public buckets / emulators). Endpoint overridable for
    fake-gcs-server style emulators via STORAGE_EMULATOR_HOST."""

    # refresh this many seconds before the token's stated expiry
    TOKEN_REFRESH_MARGIN_S = 120.0

    def __init__(self, endpoint: Optional[str] = None, timeout: float = 20.0):
        self.endpoint = (endpoint or os.environ.get("STORAGE_EMULATOR_HOST")
                         or "https://storage.googleapis.com").rstrip("/")
        self.timeout = timeout
        self._token: Optional[str] = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        self._token_source = "env" if self._token else None
        self._token_expiry: Optional[float] = None  # monotonic deadline
        self._probed_metadata = False

    def _token_stale(self) -> bool:
        return (self._token_expiry is not None
                and time.monotonic() >= self._token_expiry - self.TOKEN_REFRESH_MARGIN_S)  # lint: waive LR109 — GCS token expiry deadline, not self-measurement

    def _headers(self) -> dict:
        if self._token is None and not self._probed_metadata:
            # probe the metadata server ONCE; off-GCE hosts must not pay a
            # 2s timeout per storage operation
            self._probed_metadata = True
            self._metadata_token()
        elif self._token_source == "metadata" and self._token_stale():
            # GCE access tokens expire (~1h): proactively re-fetch near
            # expiry so long-running checkpoint streams never see the 401
            self._metadata_token()
        return {"Authorization": f"Bearer {self._token}"} if self._token else {}

    def _metadata_token(self) -> Optional[str]:
        import json as _json
        import urllib.request

        try:
            req = urllib.request.Request(
                "http://metadata.google.internal/computeMetadata/v1/instance/"
                "service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=2) as r:
                payload = _json.loads(r.read())
                self._token = payload["access_token"]
                self._token_source = "metadata"
                expires_in = payload.get("expires_in")
                self._token_expiry = (
                    time.monotonic() + float(expires_in) if expires_in else None)  # lint: waive LR109 — GCS token expiry deadline, not self-measurement
                return self._token
        except Exception:  # noqa: BLE001 - not on GCE
            return None

    def _refresh_token(self) -> bool:
        """Force-refresh after an auth failure: re-read the env var (it may
        have been rotated in place) and re-probe the metadata server even if
        an earlier probe failed. True if a (possibly new) token is held."""
        before = self._token
        env = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env and env != self._token:
            self._token = env
            self._token_source = "env"
            self._token_expiry = None
            return True
        self._probed_metadata = True
        self._metadata_token()
        return self._token is not None and self._token != before

    def _call(self, method: str, url: str, data: Optional[bytes] = None,
              content_type: Optional[str] = None) -> bytes:
        # transient (5xx/429/network) retries belong to the shared layer
        # wrapping the public storage ops (_guarded) — retrying here too
        # would compound the schedules into attempts^2 during an outage.
        # This layer only owns the auth lifecycle: refresh-once on 401/403.
        import urllib.error

        def _once() -> bytes:
            import urllib.request

            headers = self._headers()
            if content_type:
                headers["Content-Type"] = content_type
            req = urllib.request.Request(url, data=data, method=method,
                                         headers=headers)
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read()

        try:
            return _once()
        except urllib.error.HTTPError as e:
            if e.code in (401, 403) and self._refresh_token():
                # expired/rotated credentials: retry exactly once with the
                # fresh token; a second auth failure is a real config error
                return _once()
            raise

    @staticmethod
    def _q(name: str) -> str:
        import urllib.parse

        return urllib.parse.quote(name, safe="")

    def download(self, bucket: str, name: str) -> bytes:
        return self._call(
            "GET", f"{self.endpoint}/storage/v1/b/{bucket}/o/{self._q(name)}?alt=media")

    def upload(self, bucket: str, name: str, data: bytes) -> None:
        self._call(
            "POST",
            f"{self.endpoint}/upload/storage/v1/b/{bucket}/o"
            f"?uploadType=media&name={self._q(name)}",
            data=data, content_type="application/octet-stream")

    def delete(self, bucket: str, name: str) -> None:
        self._call(
            "DELETE", f"{self.endpoint}/storage/v1/b/{bucket}/o/{self._q(name)}")

    def exists(self, bucket: str, name: str) -> bool:
        import urllib.error

        try:
            self._call(
                "GET", f"{self.endpoint}/storage/v1/b/{bucket}/o/{self._q(name)}")
            return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def list(self, bucket: str, prefix: str,
             delimiter: Optional[str] = None) -> tuple[list[str], list[str]]:
        """(object names, sub-prefixes) under prefix, paginated."""
        import json as _json

        names: list[str] = []
        prefixes: list[str] = []
        token: Optional[str] = None
        while True:
            url = (f"{self.endpoint}/storage/v1/b/{bucket}/o"
                   f"?prefix={self._q(prefix)}")
            if delimiter:
                url += f"&delimiter={self._q(delimiter)}"
            if token:
                url += f"&pageToken={token}"
            resp = _json.loads(self._call("GET", url) or b"{}")
            names.extend(i["name"] for i in resp.get("items", []))
            prefixes.extend(resp.get("prefixes", []))
            token = resp.get("nextPageToken")
            if not token:
                return names, prefixes


def _parse_s3(path: str) -> Optional[tuple[str, str]]:
    if not path.startswith("s3://"):
        return None
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key.rstrip("/")


def _parse_gcs(path: str) -> Optional[tuple[str, str]]:
    if not path.startswith("gs://"):
        return None
    rest = path[len("gs://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key.rstrip("/")


def _local(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


# ------------------------------------------------------------------ bytes


def read_bytes(path: str) -> bytes:
    def _do(verdict) -> bytes:
        s3 = _parse_s3(path)
        if s3:
            data = _get_s3().get_object(Bucket=s3[0], Key=s3[1])["Body"].read()
        else:
            gcs = _parse_gcs(path)
            if gcs:
                data = _get_gcs().download(gcs[0], gcs[1])
            else:
                with open(_local(path), "rb") as f:
                    data = f.read()
        if verdict and verdict[0] == "corrupt":
            data = _apply_corruption(data, str(verdict[1]))
        return data

    return _guarded_v("storage.get", path, _do)


def _multipart_threshold() -> int:
    from ..config import config

    v = config().get("storage.multipart-threshold-bytes")
    return int(v) if v is not None else MULTIPART_DEFAULT


S3_MIN_PART = 5 * 1024 * 1024  # AWS: every non-final part must be >= 5 MiB


def _multipart_part_size() -> int:
    from ..config import config

    v = config().get("storage.multipart-part-size-bytes")
    if v is not None:
        return int(v)
    # part size decoupled from the trigger threshold: a small threshold
    # must not produce parts real S3 rejects with EntityTooSmall
    return max(_multipart_threshold(), S3_MIN_PART)


def _s3_multipart_put(client, bucket: str, key: str, data: bytes,
                      part_size: int) -> None:
    """Multipart upload with abort-on-error (reference lib.rs:317
    start/add/close multipart path)."""
    up = client.create_multipart_upload(Bucket=bucket, Key=key)
    upload_id = up["UploadId"]
    try:
        parts = []
        num = 1
        for off in range(0, len(data), part_size):
            fault_point("storage.multipart", key=key, part=num)
            r = client.upload_part(
                Bucket=bucket, Key=key, UploadId=upload_id, PartNumber=num,
                Body=data[off:off + part_size])
            parts.append({"PartNumber": num, "ETag": r["ETag"]})
            num += 1
        client.complete_multipart_upload(
            Bucket=bucket, Key=key, UploadId=upload_id,
            MultipartUpload={"Parts": parts})
    except Exception:
        # never leave a half-finished upload accruing storage charges
        try:
            client.abort_multipart_upload(
                Bucket=bucket, Key=key, UploadId=upload_id)
        except Exception as e2:  # noqa: BLE001
            _log.warning("abort_multipart_upload(%s) failed: %s", key, e2)
        raise


def write_bytes(path: str, data: bytes) -> dict:
    """Write one artifact and return its integrity envelope {crc, len,
    algo}, computed on the TRUE bytes BEFORE any injected corruption — a
    corrupt-on-put chaos fault is therefore detectable on read, exactly
    like a real truncated upload."""
    env = checksum_of(data)

    def _do(verdict) -> None:
        payload = data
        if verdict and verdict[0] == "corrupt":
            payload = _apply_corruption(payload, str(verdict[1]))
        s3 = _parse_s3(path)
        if s3:
            client = _get_s3()
            threshold = _multipart_threshold()
            if (len(payload) > threshold
                    and hasattr(client, "create_multipart_upload")):
                _s3_multipart_put(client, s3[0], s3[1], payload,
                                  _multipart_part_size())
            else:
                client.put_object(Bucket=s3[0], Key=s3[1], Body=payload)
            return
        gcs = _parse_gcs(path)
        if gcs:
            _get_gcs().upload(gcs[0], gcs[1], payload)
            return
        p = _local(path)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, p)

    _guarded_v("storage.put", path, _do)
    return env


def read_text(path: str) -> str:
    return read_bytes(path).decode("utf-8")


def write_text(path: str, text: str) -> dict:
    return write_bytes(path, text.encode("utf-8"))


def verify_mode() -> str:
    """``state.integrity.verify``: ``restore`` (default — verify artifacts
    on the restore path only), ``always`` (every checkpointed read), or
    ``off`` (trust the store; fsck still verifies explicitly)."""
    from ..config import config

    return str(config().get("state.integrity.verify") or "restore")


# -------------------------------------------------------------- directory


def makedirs(path: str) -> None:
    if _parse_s3(path) or _parse_gcs(path):
        return  # prefixes need no creation
    os.makedirs(_local(path), exist_ok=True)


def _is_not_found(exc: Exception) -> bool:
    """True only for a definitive not-found; transient S3 failures
    (throttling, auth) must propagate — mapping them to "absent" would make
    a committed checkpoint look incomplete and restore an older epoch."""
    if isinstance(exc, (KeyError, FileNotFoundError)):
        return True  # injected fake clients
    resp = getattr(exc, "response", None)
    if isinstance(resp, dict):
        code = str(resp.get("Error", {}).get("Code", ""))
        status = resp.get("ResponseMetadata", {}).get("HTTPStatusCode")
        return code in ("404", "NoSuchKey", "NotFound") or status == 404
    return False


def exists(path: str) -> bool:
    s3 = _parse_s3(path)
    if s3:
        try:
            _get_s3().head_object(Bucket=s3[0], Key=s3[1])
            return True
        except Exception as e:
            if _is_not_found(e):
                return False
            raise
    gcs = _parse_gcs(path)
    if gcs:
        return _get_gcs().exists(gcs[0], gcs[1])
    return os.path.exists(_local(path))


def isdir(path: str) -> bool:
    def _do() -> bool:
        s3 = _parse_s3(path)
        if s3:
            bucket, key = s3
            resp = _get_s3().list_objects_v2(
                Bucket=bucket, Prefix=key + "/", MaxKeys=1)
            return resp.get("KeyCount", len(resp.get("Contents", []))) > 0
        gcs = _parse_gcs(path)
        if gcs:
            names, prefixes = _get_gcs().list(gcs[0], gcs[1] + "/")
            return bool(names or prefixes)
        return os.path.isdir(_local(path))

    return _guarded("storage.list", path, _do)


def listdir(path: str) -> list[str]:
    """Immediate children (files and sub-prefixes), names only."""
    return _guarded("storage.list", path, lambda: _listdir_once(path))


def _listdir_once(path: str) -> list[str]:
    s3 = _parse_s3(path)
    if s3:
        bucket, key = s3
        prefix = key + "/" if key else ""
        names = set()
        token = None
        while True:
            kwargs = dict(Bucket=bucket, Prefix=prefix, Delimiter="/")
            if token:
                kwargs["ContinuationToken"] = token
            resp = _get_s3().list_objects_v2(**kwargs)
            for c in resp.get("Contents", []):
                names.add(c["Key"][len(prefix):])
            for p in resp.get("CommonPrefixes", []):
                names.add(p["Prefix"][len(prefix):].rstrip("/"))
            token = resp.get("NextContinuationToken")
            if not token:
                break
        return sorted(n for n in names if n)
    gcs = _parse_gcs(path)
    if gcs:
        bucket, key = gcs
        prefix = key + "/" if key else ""
        onames, oprefixes = _get_gcs().list(bucket, prefix, delimiter="/")
        out = {n[len(prefix):] for n in onames}
        out.update(p[len(prefix):].rstrip("/") for p in oprefixes)
        return sorted(n for n in out if n)
    return sorted(os.listdir(_local(path)))


def remove(path: str) -> None:
    def _do() -> None:
        s3 = _parse_s3(path)
        if s3:
            _get_s3().delete_object(Bucket=s3[0], Key=s3[1])
            return
        gcs = _parse_gcs(path)
        if gcs:
            _get_gcs().delete(gcs[0], gcs[1])
            return
        os.remove(_local(path))

    _guarded("storage.delete", path, _do)


def rmtree(path: str) -> None:
    """Best-effort recursive delete (GC path — mirrors the local branch's
    ignore_errors; a transient S3 failure must not crash the engine over a
    cleanup step). S3 keys go through batched delete_objects (1000/request)
    when the client supports it."""
    s3 = _parse_s3(path)
    if s3:
        bucket, key = s3
        client = _get_s3()
        token = None
        errors = 0
        while True:
            try:
                kwargs = dict(Bucket=bucket, Prefix=key + "/")
                if token:
                    kwargs["ContinuationToken"] = token
                resp = client.list_objects_v2(**kwargs)
            except Exception as e:  # noqa: BLE001
                # without a continuation token we cannot advance; stop, but
                # leave a trail so checkpoint-GC leaks are visible
                _log.warning("rmtree(%s): list failed, sweep aborted: %s", path, e)
                return
            keys = [c["Key"] for c in resp.get("Contents", [])]
            batched = keys and hasattr(client, "delete_objects")
            for chunk in ([keys[i:i + 1000] for i in range(0, len(keys), 1000)]
                          if batched else [[k] for k in keys]):
                try:
                    if batched:
                        client.delete_objects(
                            Bucket=bucket,
                            Delete={"Objects": [{"Key": k} for k in chunk]})
                    else:
                        client.delete_object(Bucket=bucket, Key=chunk[0])
                except Exception as e:  # noqa: BLE001
                    # keep sweeping the remaining batches — one transient
                    # failure must not abandon the whole prefix
                    errors += 1
                    if errors <= 3:
                        _log.warning("rmtree(%s): delete batch failed: %s", path, e)
            token = resp.get("NextContinuationToken")
            if not token:
                break
        if errors:
            _log.warning("rmtree(%s): %d delete batch(es) failed", path, errors)
        return
    gcs = _parse_gcs(path)
    if gcs:
        bucket, key = gcs
        client = _get_gcs()
        try:
            names, _prefixes = client.list(bucket, key + "/")
        except Exception as e:  # noqa: BLE001
            _log.warning("rmtree(%s): list failed, sweep aborted: %s", path, e)
            return
        errors = 0
        for n in names:
            try:
                client.delete(bucket, n)
            except Exception as e:  # noqa: BLE001
                errors += 1
                if errors <= 3:
                    _log.warning("rmtree(%s): delete %s failed: %s", path, n, e)
        if errors:
            _log.warning("rmtree(%s): %d delete(s) failed", path, errors)
        return
    shutil.rmtree(_local(path), ignore_errors=True)
