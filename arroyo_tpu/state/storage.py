"""URL-dispatched object storage behind checkpoints and file connectors.

Equivalent of crates/arroyo-storage (StorageProvider, lib.rs:33 /
BackendConfig, lib.rs:180): one path-string API that reads/writes local
filesystems or S3-compatible object stores depending on the URL scheme —
``/abs/path`` or ``file://`` for local, ``s3://bucket/prefix`` for object
storage (boto3 when available; tests inject a fake client via
``set_s3_client``). Directory-shaped operations (listdir/isdir/rmtree) are
emulated on S3 with delimiter listings, mirroring how the reference treats
checkpoint paths as key prefixes.

All writes are atomic-publish: local files go through tmp + os.replace,
S3 puts are atomic by the service's semantics.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Optional

_log = logging.getLogger("arroyo_tpu.storage")

_s3_client = None


def set_s3_client(client) -> None:
    """Inject an S3 client (tests: an in-memory fake; production may pass a
    configured boto3 client to control credentials/endpoints)."""
    global _s3_client
    _s3_client = client


def _get_s3():
    global _s3_client
    if _s3_client is None:
        try:
            import boto3  # type: ignore

            _s3_client = boto3.client("s3")
        except ImportError as e:
            raise RuntimeError(
                "s3:// storage requires boto3 (not installed) or an injected "
                "client via arroyo_tpu.state.storage.set_s3_client"
            ) from e
    return _s3_client


def _parse_s3(path: str) -> Optional[tuple[str, str]]:
    if not path.startswith("s3://"):
        return None
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key.rstrip("/")


def _local(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


# ------------------------------------------------------------------ bytes


def read_bytes(path: str) -> bytes:
    s3 = _parse_s3(path)
    if s3:
        return _get_s3().get_object(Bucket=s3[0], Key=s3[1])["Body"].read()
    with open(_local(path), "rb") as f:
        return f.read()


def write_bytes(path: str, data: bytes) -> None:
    s3 = _parse_s3(path)
    if s3:
        _get_s3().put_object(Bucket=s3[0], Key=s3[1], Body=data)
        return
    p = _local(path)
    tmp = p + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, p)


def read_text(path: str) -> str:
    return read_bytes(path).decode("utf-8")


def write_text(path: str, text: str) -> None:
    write_bytes(path, text.encode("utf-8"))


# -------------------------------------------------------------- directory


def makedirs(path: str) -> None:
    if _parse_s3(path):
        return  # prefixes need no creation
    os.makedirs(_local(path), exist_ok=True)


def _is_not_found(exc: Exception) -> bool:
    """True only for a definitive not-found; transient S3 failures
    (throttling, auth) must propagate — mapping them to "absent" would make
    a committed checkpoint look incomplete and restore an older epoch."""
    if isinstance(exc, (KeyError, FileNotFoundError)):
        return True  # injected fake clients
    resp = getattr(exc, "response", None)
    if isinstance(resp, dict):
        code = str(resp.get("Error", {}).get("Code", ""))
        status = resp.get("ResponseMetadata", {}).get("HTTPStatusCode")
        return code in ("404", "NoSuchKey", "NotFound") or status == 404
    return False


def exists(path: str) -> bool:
    s3 = _parse_s3(path)
    if s3:
        try:
            _get_s3().head_object(Bucket=s3[0], Key=s3[1])
            return True
        except Exception as e:
            if _is_not_found(e):
                return False
            raise
    return os.path.exists(_local(path))


def isdir(path: str) -> bool:
    s3 = _parse_s3(path)
    if s3:
        bucket, key = s3
        resp = _get_s3().list_objects_v2(
            Bucket=bucket, Prefix=key + "/", MaxKeys=1)
        return resp.get("KeyCount", len(resp.get("Contents", []))) > 0
    return os.path.isdir(_local(path))


def listdir(path: str) -> list[str]:
    """Immediate children (files and sub-prefixes), names only."""
    s3 = _parse_s3(path)
    if s3:
        bucket, key = s3
        prefix = key + "/" if key else ""
        names = set()
        token = None
        while True:
            kwargs = dict(Bucket=bucket, Prefix=prefix, Delimiter="/")
            if token:
                kwargs["ContinuationToken"] = token
            resp = _get_s3().list_objects_v2(**kwargs)
            for c in resp.get("Contents", []):
                names.add(c["Key"][len(prefix):])
            for p in resp.get("CommonPrefixes", []):
                names.add(p["Prefix"][len(prefix):].rstrip("/"))
            token = resp.get("NextContinuationToken")
            if not token:
                break
        return sorted(n for n in names if n)
    return sorted(os.listdir(_local(path)))


def remove(path: str) -> None:
    s3 = _parse_s3(path)
    if s3:
        _get_s3().delete_object(Bucket=s3[0], Key=s3[1])
        return
    os.remove(_local(path))


def rmtree(path: str) -> None:
    """Best-effort recursive delete (GC path — mirrors the local branch's
    ignore_errors; a transient S3 failure must not crash the engine over a
    cleanup step). S3 keys go through batched delete_objects (1000/request)
    when the client supports it."""
    s3 = _parse_s3(path)
    if s3:
        bucket, key = s3
        client = _get_s3()
        token = None
        errors = 0
        while True:
            try:
                kwargs = dict(Bucket=bucket, Prefix=key + "/")
                if token:
                    kwargs["ContinuationToken"] = token
                resp = client.list_objects_v2(**kwargs)
            except Exception as e:  # noqa: BLE001
                # without a continuation token we cannot advance; stop, but
                # leave a trail so checkpoint-GC leaks are visible
                _log.warning("rmtree(%s): list failed, sweep aborted: %s", path, e)
                return
            keys = [c["Key"] for c in resp.get("Contents", [])]
            batched = keys and hasattr(client, "delete_objects")
            for chunk in ([keys[i:i + 1000] for i in range(0, len(keys), 1000)]
                          if batched else [[k] for k in keys]):
                try:
                    if batched:
                        client.delete_objects(
                            Bucket=bucket,
                            Delete={"Objects": [{"Key": k} for k in chunk]})
                    else:
                        client.delete_object(Bucket=bucket, Key=chunk[0])
                except Exception as e:  # noqa: BLE001
                    # keep sweeping the remaining batches — one transient
                    # failure must not abandon the whole prefix
                    errors += 1
                    if errors <= 3:
                        _log.warning("rmtree(%s): delete batch failed: %s", path, e)
            token = resp.get("NextContinuationToken")
            if not token:
                break
        if errors:
            _log.warning("rmtree(%s): %d delete batch(es) failed", path, errors)
        return
    shutil.rmtree(_local(path), ignore_errors=True)
