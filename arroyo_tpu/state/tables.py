"""Keyed / timed state tables with Parquet checkpoints.

Equivalent of crates/arroyo-state: TableManager (tables/table_manager.rs:35),
ExpiringTimeKeyTable (tables/expiring_time_key_map.rs:47), GlobalKeyedTable
(tables/global_keyed_map.rs:42), checkpoint path scheme (tables/mod.rs:20-43):

    {job}/checkpoints/checkpoint-{epoch:07}/operator-{op}/table-{name}-{subtask:03}

Restore filters Parquet files by (a) watermark-retention overlap and (b) the
restoring subtask's routing-key-range overlap, which is what makes restore at
a different parallelism (rescaling) work — same semantics as the reference
(expiring_time_key_map.rs restore path; tables/mod.rs:106-110).

In the TPU design the authoritative window state lives in HBM between
watermarks; operators mirror it into these host tables at barrier time only
(handle_checkpoint), so snapshots are taken at consistent step boundaries.
"""

from __future__ import annotations

import io
import json
import logging
import os
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

import numpy as np

# Parquet is the default checkpoint codec (reference-compatible state
# files, crates/arroyo-state/src/parquet.rs:24); .npz remains as the
# fallback codec via ``checkpoint.file-format = "npz"`` or when pyarrow is
# unavailable. (A round-2 comment here blamed pyarrow for flaky segfaults
# under concurrent checkpoint/restore; re-testing the full smoke pattern
# found none — the real defect was the then-codec stringifying object
# columns, which lost nullable-int typing. The IO lock stays as cheap
# insurance around the C++ IO paths.)
_PARQUET_IO_LOCK = threading.Lock()

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch, Schema
from ..types import TaskInfo
from . import storage

_log = logging.getLogger("arroyo_tpu.state")


class RestoreError(RuntimeError):
    """A restore-path read failed, with enough context to say exactly what
    was skipped: the epoch, the operator, the artifact path, and the
    underlying cause (an IntegrityError, a codec error, a missing file).
    The fallback ladder and the event feed both render from this."""

    def __init__(self, epoch, operator: str, path: str, cause: Exception):
        super().__init__(
            f"restore of operator {operator!r} from epoch {epoch} failed "
            f"at {path}: {cause}")
        self.epoch = epoch
        self.operator = operator
        self.path = path
        self.cause = cause


def _should_verify(restore: bool = False) -> bool:
    """Whether this read verifies its integrity envelope, per
    ``state.integrity.verify``: off = never, always = every read,
    restore (default) = restore-path reads only."""
    mode = storage.verify_mode()
    if mode == "off":
        return False
    if mode == "always":
        return True
    return restore


def dump_json_with_integrity(obj: dict) -> str:
    """Serialize a JSON artifact with an embedded ``__integrity__``
    envelope over its canonical (sorted-keys) form, so the artifact
    self-verifies without a sidecar."""
    body = json.dumps(obj, sort_keys=True)
    env = storage.checksum_of(body.encode("utf-8"))
    return json.dumps({**obj, "__integrity__": env})


def load_json_with_integrity(text: str, path: str, verify: bool) -> dict:
    """Parse a JSON artifact, verifying the embedded envelope when asked.
    Artifacts written before the envelope existed carry no key and pass
    through. Raises storage.IntegrityError on mismatch."""
    obj = json.loads(text)
    env = obj.pop("__integrity__", None)
    if env is not None and verify:
        storage.verify_envelope(
            json.dumps(obj, sort_keys=True).encode("utf-8"), env, path)
    return obj


def _parquet_available() -> bool:
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401

        return True
    except ImportError:
        return False


def _checkpoint_format() -> str:
    from ..config import config

    fmt = config().get("checkpoint.file-format", "parquet")
    if fmt == "parquet" and not _parquet_available():
        return "npz"
    return fmt


def _format_of(path: str) -> str:
    """Codec of an existing state file, from its extension — restore must
    read whatever the WRITER used (a checkpoint taken under the npz
    fallback stays readable after pyarrow appears, and vice versa)."""
    return "npz" if path.endswith(".npz") else "parquet"


def write_columnar(path: str, columns: dict, footer: bool = False) -> dict:
    """Write named columns to ``path`` in the configured codec. Object
    columns keep their python value types: pyarrow type inference for
    parquet (nullable ints stay ints), a pickled sidecar for npz.

    Returns the integrity envelope {crc, len, algo} of the written bytes
    for the caller's manifest. ``footer=True`` instead appends the
    self-describing integrity trailer (storage.wrap_footer) — for
    artifacts like spill runs that outlive any one epoch's manifest."""
    if _checkpoint_format() == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        arrays, names = [], []
        for name, col in columns.items():
            if col.dtype == object:
                vals = [v.item() if isinstance(v, np.generic) else v for v in col]
                try:
                    arrays.append(pa.array(vals))
                    names.append(name)
                except (pa.ArrowInvalid, pa.ArrowTypeError):
                    # heterogeneous python values: exact round trip via a
                    # per-value pickled binary column (name-suffix marker)
                    arrays.append(pa.array(
                        [None if v is None else pickle.dumps(v) for v in vals],
                        type=pa.binary(),
                    ))
                    names.append(name + "__pickled")
            else:
                arrays.append(pa.array(col))
                names.append(name)
        buf = io.BytesIO()
        with _PARQUET_IO_LOCK:
            pq.write_table(pa.table(arrays, names=names), buf)
        payload = buf.getvalue()
        if footer:
            payload = storage.wrap_footer(payload)
        return storage.write_bytes(path, payload)
    dense = {}
    objcols: dict[str, list] = {}
    for name, col in columns.items():
        if col.dtype == object:
            # keep python values as-is (ints stay ints); only unwrap numpy
            # scalars so the pickle round-trips cleanly
            objcols[name] = [v.item() if isinstance(v, np.generic) else v for v in col]
        else:
            dense[name] = col
    if objcols:
        dense["__objcols__"] = np.frombuffer(pickle.dumps(objcols), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **dense)
    payload = buf.getvalue()
    if footer:
        payload = storage.wrap_footer(payload)
    return storage.write_bytes(path, payload)


def read_columnar(path: str, expect: Optional[dict] = None,
                  restore: bool = False) -> dict:
    """Read a columnar state file. ``expect`` is the integrity envelope a
    manifest recorded for this file; a self-describing footer (spill runs)
    is stripped unconditionally so the codecs never see it. Verification
    of either form is gated by ``state.integrity.verify`` (``restore``
    marks this read as a restore-path read)."""
    verify = _should_verify(restore)
    data = storage.read_bytes(path)
    if expect is not None and verify and "crc" in expect:
        storage.verify_envelope(data, expect, path)
    data = storage.unwrap_footer(data, path, verify=verify)
    if _format_of(path) == "parquet":
        import pyarrow.parquet as pq

        # bytes fetched before taking the parquet lock (LR105): the storage
        # read can block on the network and must not serialize other readers
        with _PARQUET_IO_LOCK:
            table = pq.read_table(io.BytesIO(data), use_threads=False)
        cols: dict[str, np.ndarray] = {}
        for name in table.column_names:
            arr = table.column(name)
            if name.endswith("__pickled"):
                from ..batch import object_column

                cols[name[: -len("__pickled")]] = object_column(
                    None if v is None else pickle.loads(v) for v in arr.to_pylist()
                )
            elif str(arr.type) in ("string", "large_string", "null") or arr.null_count > 0:
                # non-numeric or null-carrying: preserve python values
                # (to_numpy would coerce nullable ints to float64 + NaN)
                from ..batch import object_column

                cols[name] = object_column(arr.to_pylist())
            else:
                cols[name] = np.asarray(arr.to_numpy(zero_copy_only=False))
        return cols
    npz = np.load(io.BytesIO(data), allow_pickle=False)
    cols = {name: npz[name] for name in npz.files if name != "__objcols__"}
    if "__objcols__" in npz.files:
        from ..batch import object_column

        objcols = pickle.loads(npz["__objcols__"].tobytes())
        for name, vals in objcols.items():
            cols[name] = object_column(vals)
    return cols


def checkpoint_dir(storage_url: str, job_id: str, epoch) -> str:
    """epoch: int, or the string "final" for drained-source snapshots."""
    name = f"checkpoint-{epoch:07d}" if isinstance(epoch, int) else f"checkpoint-{epoch}"
    return os.path.join(storage_url, job_id, "checkpoints", name)


def operator_dir(storage_url: str, job_id: str, epoch, node_id: str) -> str:
    return os.path.join(checkpoint_dir(storage_url, job_id, epoch), f"operator-{node_id}")


class GlobalKeyedTable:
    """Small K/V state, full copy per checkpoint (global_keyed_map.rs:42).
    Used for source offsets, watermark-generator state, session metadata."""

    def __init__(self, name: str):
        self.name = name
        self.data: dict[Any, Any] = {}

    def get(self, key, default=None):
        return self.data.get(key, default)

    def insert(self, key, value) -> None:
        self.data[key] = value

    def delete(self, key) -> None:
        self.data.pop(key, None)

    def items(self):
        return self.data.items()

    # -- checkpoint ---------------------------------------------------------

    def write_checkpoint(self, path: str) -> dict:
        env = storage.write_bytes(path, pickle.dumps(self.data))
        return {"file": os.path.basename(path), "kind": "global_keyed", **env}

    def load_files(self, entries: Iterable) -> None:
        """Entries are paths, or (path, file-meta) pairs whose meta may
        carry the integrity envelope recorded at checkpoint time."""
        for e in entries:
            p, fm = e if isinstance(e, tuple) else (e, None)
            data = storage.read_bytes(p)
            if fm is not None and "crc" in fm and _should_verify(True):
                storage.verify_envelope(data, fm, p)
            self.data.update(pickle.loads(data))


class ExpiringTimeKeyTable:
    """Batches bucketed by event time with retention
    (expiring_time_key_map.rs:47). Holds columnar batches; rows carry
    _timestamp and (if keyed) _key columns used for expiry and rescale."""

    def __init__(self, name: str, retention_micros: int = 0):
        self.name = name
        self.retention_micros = retention_micros
        self.batches: list[Batch] = []

    def insert(self, batch: Batch) -> None:
        if batch.num_rows:
            self.batches.append(batch)

    def replace_all(self, batches: list[Batch]) -> None:
        self.batches = [b for b in batches if b.num_rows]

    def all_batches(self) -> list[Batch]:
        return list(self.batches)

    def expire(self, watermark_micros: int) -> None:
        """Drop rows older than watermark - retention
        (expiring_time_key_map.rs:816-849)."""
        cutoff = watermark_micros - self.retention_micros
        kept = []
        for b in self.batches:
            mask = b.timestamps >= cutoff
            if mask.all():
                kept.append(b)
            elif mask.any():
                kept.append(b.filter(mask))
        self.batches = kept

    def total_rows(self) -> int:
        return sum(b.num_rows for b in self.batches)

    # -- checkpoint ---------------------------------------------------------

    def write_checkpoint(self, path: str) -> Optional[dict]:
        if not self.batches:
            return None
        merged = Batch.concat(self.batches)
        env = write_columnar(path, merged.columns)
        ts = merged.timestamps
        meta = {
            "file": os.path.basename(path),
            "kind": "expiring_time_key",
            "min_timestamp": int(ts.min()),
            "max_timestamp": int(ts.max()),
            **env,
        }
        if KEY_FIELD in merged:
            k = merged.keys
            meta["min_key"] = int(k.min())
            meta["max_key"] = int(k.max())
        return meta

    def load_files(
        self,
        entries: Iterable[tuple[str, dict]],
        key_range: tuple[int, int],
        watermark_micros: Optional[int],
    ) -> None:
        """Restore: read files overlapping our key range & retention window."""
        cutoff = None
        if watermark_micros is not None and self.retention_micros:
            cutoff = watermark_micros - self.retention_micros
        lo, hi = key_range
        for path, meta in entries:
            if cutoff is not None and meta.get("max_timestamp", 1 << 62) < cutoff:
                continue
            if "min_key" in meta and (meta["min_key"] > hi or meta["max_key"] < lo):
                continue
            cols = read_columnar(path, expect=meta, restore=True)
            batch = Batch(cols)
            if KEY_FIELD in batch:
                keys = batch.keys
                mask = (keys >= np.uint64(lo)) & (keys <= np.uint64(hi))
                if not mask.all():
                    batch = batch.filter(mask)
            if cutoff is not None and batch.num_rows:
                mask = batch.timestamps >= cutoff
                if not mask.all():
                    batch = batch.filter(mask)
            if batch.num_rows:
                self.batches.append(batch)


class TableManager:
    """Per-subtask state facade (tables/table_manager.rs:35)."""

    def __init__(self, task_info: TaskInfo, storage_url: str):
        self.task_info = task_info
        self.storage_url = storage_url
        self.globals: dict[str, GlobalKeyedTable] = {}
        self.expiring: dict[str, ExpiringTimeKeyTable] = {}

    def global_keyed(self, name: str) -> GlobalKeyedTable:
        if name not in self.globals:
            self.globals[name] = GlobalKeyedTable(name)
        return self.globals[name]

    def expiring_time_key(self, name: str, retention_micros: int = 0) -> ExpiringTimeKeyTable:
        if name not in self.expiring:
            self.expiring[name] = ExpiringTimeKeyTable(name, retention_micros)
        t = self.expiring[name]
        if retention_micros:
            t.retention_micros = retention_micros
        return t

    # -- checkpoint / restore ----------------------------------------------

    def checkpoint(self, epoch: int, watermark_micros: Optional[int]) -> dict:
        """Write all tables; returns subtask metadata for the engine to merge
        (reference: flusher write + OperatorCheckpointMetadata merge)."""
        ti = self.task_info
        opdir = operator_dir(self.storage_url, ti.job_id, epoch, ti.node_id)
        storage.makedirs(opdir)
        sub = f"{ti.subtask_index:03d}"
        files = []
        for name, table in self.globals.items():
            meta = table.write_checkpoint(os.path.join(opdir, f"table-{name}-{sub}.bin"))
            meta["table"] = name
            if name.endswith("__spill"):
                # tiered-state manifest table (state/spill.py): lift the
                # referenced run file names into the checkpoint metadata so
                # spill-run GC can see liveness without unpickling tables
                from .spill import manifest_run_files

                meta["spill_runs"] = manifest_run_files(table.data)
            files.append(meta)
        ext = "parquet" if _checkpoint_format() == "parquet" else "npz"
        for name, table in self.expiring.items():
            meta = table.write_checkpoint(os.path.join(opdir, f"table-{name}-{sub}.{ext}"))
            if meta is not None:
                meta["table"] = name
                meta["retention_micros"] = table.retention_micros
                files.append(meta)
        meta = {
            "node_id": ti.node_id,
            "subtask_index": ti.subtask_index,
            "watermark_micros": watermark_micros,
            "files": files,
        }
        # self-checksummed sidecar; the envelope of the WRITTEN bytes rides
        # back in the (unwritten) "sidecar" entry so the job-level marker's
        # integrity manifest can cover the sidecar file itself
        env = storage.write_text(os.path.join(opdir, f"metadata-{sub}.json"),
                                 dump_json_with_integrity(meta))
        return {**meta, "sidecar": {"file": f"metadata-{sub}.json", **env}}

    def restore(self, epoch: int, table_specs: list,
                mapping: Optional[dict] = None) -> Optional[int]:
        """Load state written at ``epoch`` (possibly at different parallelism).

        Subtasks absent from the epoch snapshot (they drained before the
        barrier — e.g. a source that hit EOF) are filled from the "final"
        snapshot written at graceful finish: a drained task's state is
        constant after EOF, and everything it emitted was processed by
        downstream tasks before their epoch barriers, so its final state is
        consistent with any later epoch.

        ``mapping`` is this node's entry of a live-evolution mapping
        (analysis/plan_diff.py): ``{"action": "carried", "from": <old node
        id>, "tables": [...]}`` redirects the read to the predecessor
        plan's operator directory (the plan-diff pass proved the layouts
        identical); ``{"action": "rebuilt"}`` restores nothing — the state
        re-derives from replay. Under a mapping, checkpoint files for
        tables the new operator does not declare are explicitly dropped
        and logged, never silently resurrected.

        Returns the restored watermark (min across prior subtasks), if any.
        """
        ti = self.task_info
        src_node = ti.node_id
        if mapping:
            action = mapping.get("action")
            if action == "rebuilt":
                _log.info(
                    "evolve: %s state rebuilt by replay (no carry-over from "
                    "epoch %s)", ti.node_id, epoch)
                return None
            if action == "carried" and mapping.get("from"):
                src_node = str(mapping["from"])
                if src_node != ti.node_id:
                    _log.info("evolve: %s restores carried state from "
                              "predecessor operator %s", ti.node_id, src_node)

        def read_metas(d: str) -> list:
            out = []
            if not storage.isdir(d):
                return out
            for fn in storage.listdir(d):
                if fn.startswith("metadata-") and fn.endswith(".json"):
                    p = os.path.join(d, fn)
                    try:
                        m = load_json_with_integrity(
                            storage.read_text(p), p, _should_verify(True))
                    except Exception as e:  # noqa: BLE001 - context for the ladder
                        raise RestoreError(epoch, ti.node_id, p, e) from e
                    m["__dir__"] = d
                    out.append(m)
            return out

        opdir = operator_dir(self.storage_url, ti.job_id, epoch, src_node)
        metas = read_metas(opdir)
        have_subtasks = {m["subtask_index"] for m in metas}
        final_dir = operator_dir(self.storage_url, ti.job_id, "final", src_node)
        metas += [
            m for m in read_metas(final_dir) if m["subtask_index"] not in have_subtasks
        ]
        if not metas:
            return None
        watermarks = [m["watermark_micros"] for m in metas if m.get("watermark_micros") is not None]
        restored_wm = min(watermarks) if watermarks else None
        spec_by_name = {s.name: s for s in table_specs}
        by_table: dict[str, list[tuple[str, dict, str]]] = {}
        for m in metas:
            for fmeta in m["files"]:
                by_table.setdefault(fmeta["table"], []).append(
                    (os.path.join(m["__dir__"], fmeta["file"]), fmeta, m["__dir__"])
                )
        # crash-consistent compaction rule: once a checkpoint dir holds a
        # generation>=1 (merged) entry for a table, that dir's generation-0
        # entries are stale leftovers of a compaction torn mid-rewrite — the
        # merged file already holds their rows, so reading both would
        # double-count state. Scoped per directory: the "final" snapshot dir
        # is never compacted, and its gen-0 state must survive a compacted
        # epoch dir sitting next to it.
        for tname, entries in list(by_table.items()):
            compacted_dirs = {d for _p, fm, d in entries
                              if int(fm.get("generation", 0)) >= 1}
            if compacted_dirs:
                by_table[tname] = [
                    (p, fm, d) for p, fm, d in entries
                    if int(fm.get("generation", 0)) >= 1 or d not in compacted_dirs
                ]
        # "final"-snapshot fallback files must load BEFORE the epoch's own
        # files: global-keyed loads merge dict-style (last write wins), and a
        # drained subtask's final snapshot may hold an older copy of a key a
        # live subtask kept advancing (e.g. a shared source offset) — the
        # epoch's value is the fresher one and must win the merge
        final_dir_last = final_dir
        by_table = {
            t: [(p, fm) for p, fm, _d in
                sorted(es, key=lambda pfd: pfd[2] != final_dir_last)]
            for t, es in by_table.items()
        }
        for tname, entries in by_table.items():
            spec = spec_by_name.get(tname)
            if mapping and spec is None:
                # evolution restore: a checkpointed table the evolved
                # operator no longer declares. Dropping it is the proven-
                # sound outcome (the plan-diff pass classified this node
                # carried, so its declared set IS the old set — anything
                # else is a leftover the new operator would never read);
                # explicit and logged, never silently resurrected.
                _log.warning(
                    "evolve: dropping checkpointed table %r of %s (%d "
                    "file(s)): not declared by the evolved operator",
                    tname, ti.node_id, len(entries))
                continue
            kind = entries[0][1].get("kind")
            try:
                if kind == "global_keyed":
                    self.global_keyed(tname).load_files(entries)
                else:
                    retention = spec.retention_micros if spec else entries[0][1].get("retention_micros", 0)
                    self.expiring_time_key(tname, retention).load_files(
                        entries, ti.key_range, restored_wm
                    )
            except RestoreError:
                raise
            except Exception as e:  # noqa: BLE001 - context for the ladder
                raise RestoreError(
                    epoch, ti.node_id, getattr(e, "path", entries[0][0]),
                    e) from e
        return restored_wm


def compact_operator(storage_url: str, job_id: str, epoch, node_id: str) -> int:
    """Merge an operator's per-subtask state files into one file per table
    (reference: ParquetBackend::compact_operator, arroyo-state/src/parquet.rs:159
    — merges small files across checkpoints and bumps the generation).

    Snapshots here are self-contained per epoch, so compaction merges the
    per-subtask shards of one epoch. The merged file (generation 1) is
    assigned to subtask 0's metadata; other subtasks' file lists are
    cleared (their watermarks are preserved), so a later restore reads the
    data exactly once and re-shards it by routing-key range.
    Returns the number of files merged away.

    Crash consistency (proved by the chaos suite): the generation-1 holder's
    metadata write is the single atomic commit point. It lands FIRST; restore
    ignores every generation-0 entry for a table once any generation>=1 entry
    exists (TableManager.restore), so a crash at any point leaves the epoch
    restorable without loss or double-reads. A re-run of compaction after a
    torn crash finishes the cleanup instead of re-merging.
    """
    opdir = operator_dir(storage_url, job_id, epoch, node_id)
    if not storage.isdir(opdir):
        return 0
    metas = []
    for fn in storage.listdir(opdir):
        if fn.startswith("metadata-") and fn.endswith(".json"):
            p = os.path.join(opdir, fn)
            metas.append((fn, load_json_with_integrity(
                storage.read_text(p), p, _should_verify())))
    if not metas:
        return 0
    removed = 0
    # orphan sweep: a table file no metadata references is a leftover of a
    # torn compaction — a stale gen-0 shard de-listed before its deletion
    # step ran, or an uncommitted merged file (about to be re-merged).
    # Either way its live rows are owned elsewhere, so it is garbage.
    referenced = {fm["file"] for _fn, m in metas for fm in m["files"]}
    for fn in storage.listdir(opdir):
        if fn.startswith("table-") and fn not in referenced:
            try:
                storage.remove(os.path.join(opdir, fn))
                removed += 1
            except FileNotFoundError:
                pass
    # resume a torn compaction: a generation>=1 entry anywhere means the
    # switch already committed for that table — finish the cleanup (drop
    # stale gen-0 entries elsewhere, delete their shard files); re-merging
    # would clobber the live merged file with partial data
    done_tables = {fm["table"] for _fn, m in metas for fm in m["files"]
                   if int(fm.get("generation", 0)) >= 1}
    for fn, m in metas:
        stale = [fm for fm in m["files"]
                 if fm["table"] in done_tables and int(fm.get("generation", 0)) == 0]
        if stale:
            m["files"] = [fm for fm in m["files"] if fm not in stale]
            storage.write_text(os.path.join(opdir, fn),
                               dump_json_with_integrity(m))
            for fm in stale:
                try:
                    storage.remove(os.path.join(opdir, fm["file"]))
                    removed += 1
                except FileNotFoundError:
                    pass
    by_table: dict[str, list[dict]] = {}
    for _fn, m in metas:
        for fmeta in m["files"]:
            if (int(fmeta.get("generation", 0)) == 0
                    and fmeta["table"] not in done_tables):
                by_table.setdefault(fmeta["table"], []).append(fmeta)
    merged_files: dict[str, dict] = {}
    ext = "parquet" if _checkpoint_format() == "parquet" else "npz"
    for tname, fmetas in by_table.items():
        if len(fmetas) < 2:
            continue
        kind = fmetas[0]["kind"]
        out_name = f"table-{tname}-compacted-g1.{'bin' if kind == 'global_keyed' else ext}"
        out_path = os.path.join(opdir, out_name)
        if kind == "global_keyed":
            data: dict = {}
            for fm in fmetas:
                data.update(pickle.loads(storage.read_bytes(os.path.join(opdir, fm["file"]))))
            env = storage.write_bytes(out_path, pickle.dumps(data))
            merged = {**fmetas[0], **env}
            if any("spill_runs" in fm for fm in fmetas):
                # a merged __spill manifest table still references every
                # subtask's runs — the GC liveness union must not shrink
                merged["spill_runs"] = sorted(
                    {rf for fm in fmetas for rf in fm.get("spill_runs", ())})
        else:
            col_parts = [read_columnar(os.path.join(opdir, fm["file"]),
                                       expect=fm) for fm in fmetas]
            names = col_parts[0].keys()
            cols = {n: np.concatenate([p[n] for p in col_parts]) for n in names}
            env = write_columnar(out_path, cols)
            merged = {**fmetas[0], **env}
            merged["min_timestamp"] = min(fm["min_timestamp"] for fm in fmetas)
            merged["max_timestamp"] = max(fm["max_timestamp"] for fm in fmetas)
            if all("min_key" in fm for fm in fmetas):
                merged["min_key"] = min(fm["min_key"] for fm in fmetas)
                merged["max_key"] = max(fm["max_key"] for fm in fmetas)
        merged["file"] = out_name
        merged["generation"] = 1
        merged_files[tname] = merged
    if not merged_files:
        return removed
    # crash safety, in commit order:
    #   1. merged data files are fully written (above) — orphans if we die
    #   2. the g1-holder metadata lands FIRST (atomic publish): from this
    #      instant restore prefers generation-1 and ignores stale gen-0
    #      entries still listed by other subtasks
    #   3. remaining metadata rewrites drop their gen-0 entries
    #   4. old shard files are deleted last
    # dying between any two steps leaves the epoch restorable with neither
    # loss nor double-reads.
    holder = min(mm["subtask_index"] for _f, mm in metas)
    ordered = sorted(metas, key=lambda fm_m: fm_m[1]["subtask_index"] != holder)
    for fn, m in ordered:
        kept = [
            fm for fm in m["files"]
            if fm["table"] not in merged_files or int(fm.get("generation", 0)) > 0
        ]
        if m["subtask_index"] == holder:
            kept.extend(merged_files.values())
        m["files"] = kept
        storage.write_text(os.path.join(opdir, fn),
                           dump_json_with_integrity(m))
    for fmetas in by_table.values():
        if len(fmetas) < 2:
            continue
        for fm in fmetas:
            try:
                storage.remove(os.path.join(opdir, fm["file"]))
                removed += 1
            except FileNotFoundError:
                pass
    return removed


def compact_job(storage_url: str, job_id: str, epoch) -> int:
    """Compact every operator of one completed checkpoint."""
    cdir = checkpoint_dir(storage_url, job_id, epoch)
    total = 0
    if not storage.isdir(cdir):
        return 0
    for fn in storage.listdir(cdir):
        if fn.startswith("operator-"):
            total += compact_operator(storage_url, job_id, epoch, fn[len("operator-"):])
    return total


QUARANTINE_MARKER = "quarantine.json"
QUARANTINED_METADATA = "metadata.json.quarantined"


def is_quarantined(storage_url: str, job_id: str, epoch: int) -> bool:
    """True when an operator must resolve this epoch before anything may
    touch it: restore skips it, GC refuses it, subsume refuses it."""
    d = checkpoint_dir(storage_url, job_id, epoch)
    return (storage.exists(os.path.join(d, QUARANTINE_MARKER))
            or storage.exists(os.path.join(d, QUARANTINED_METADATA)))


def quarantine_epoch(storage_url: str, job_id: str, epoch: int,
                     reason: str) -> None:
    """Take a corrupt/incomplete epoch out of the restore chain WITHOUT
    deleting anything: the commit marker is preserved byte-exactly under
    ``metadata.json.quarantined`` (forensics + operator resolution), a
    ``quarantine.json`` records why, and only then is ``metadata.json``
    removed so selection skips the epoch. Crash-safe in that order: a
    crash mid-quarantine leaves both markers present — the epoch is
    already quarantined (is_quarantined) and still complete-looking, and
    the next restore attempt re-converges by re-running this function
    (idempotent)."""
    d = checkpoint_dir(storage_url, job_id, epoch)
    marker = os.path.join(d, "metadata.json")
    storage.makedirs(d)
    if storage.exists(marker):
        try:
            storage.write_bytes(os.path.join(d, QUARANTINED_METADATA),
                                storage.read_bytes(marker))
        except Exception as e:  # noqa: BLE001 - marker itself unreadable
            _log.warning("quarantine epoch %s: could not preserve marker "
                         "bytes: %s", epoch, e)
    storage.write_text(
        os.path.join(d, QUARANTINE_MARKER),
        dump_json_with_integrity({"job_id": job_id, "epoch": epoch,
                                  "reason": reason}))
    if storage.exists(marker):
        try:
            storage.remove(marker)
        except FileNotFoundError:
            pass
    _log.warning("checkpoint epoch %s of job %s QUARANTINED: %s",
                 epoch, job_id, reason)


def cleanup_checkpoints(storage_url: str, job_id: str, min_epoch: int) -> int:
    """Delete checkpoints below ``min_epoch`` (reference
    parquet.rs:214 cleanup_operator + controller epoch GC). The "final"
    drained-source snapshot is always kept, and so is every QUARANTINED
    epoch — evidence of corruption awaits an operator, GC never destroys
    it. Returns dirs removed."""
    base = os.path.join(storage_url, job_id, "checkpoints")
    if not storage.isdir(base):
        return 0
    removed = 0
    for fn in storage.listdir(base):
        if not fn.startswith("checkpoint-"):
            continue
        tag = fn.split("-", 1)[1]
        if not tag.isdigit():
            continue  # "final" and friends
        if int(tag) < min_epoch:
            if is_quarantined(storage_url, job_id, int(tag)):
                continue
            storage.rmtree(os.path.join(base, fn))
            removed += 1
    return removed


def subsume_torn_epoch(storage_url: str, job_id: str, epoch: int) -> bool:
    """Remove a wedged epoch's partial shards (controller stuck-checkpoint
    recovery): some subtasks wrote state files but the epoch never went
    globally durable. Safe by the same crash-consistency rule the chaos
    suite proves for compaction — an epoch directory WITHOUT its job-level
    metadata marker is invisible to restore, so deleting it cannot lose
    state. Refuses to touch a complete epoch (marker present): those are
    restore targets and only epoch GC may drop them. Also refuses a
    QUARANTINED epoch — its marker was deliberately renamed away, but the
    directory is operator-owned evidence, not torn garbage."""
    d = checkpoint_dir(storage_url, job_id, epoch)
    if storage.exists(os.path.join(d, "metadata.json")):
        return False
    if is_quarantined(storage_url, job_id, epoch):
        return False
    if not storage.isdir(d):
        return False
    storage.rmtree(d)
    return True


def write_job_checkpoint_metadata(
    storage_url: str, job_id: str, epoch: int, extra: Optional[dict] = None
) -> str:
    """Job-level commit marker once every subtask finished its snapshot
    (reference: controller CheckpointState -> CheckpointMetadata)."""
    d = checkpoint_dir(storage_url, job_id, epoch)
    storage.makedirs(d)
    path = os.path.join(d, "metadata.json")
    payload = {"job_id": job_id, "epoch": epoch}
    if extra:
        payload.update(extra)
    # atomic publish: the marker's existence declares the epoch complete;
    # storage.write_text lands via tmp+rename locally / atomic PUT on S3.
    # The marker self-checksums (__integrity__) so a torn/corrupted write
    # is detectable, not just unparseable.
    storage.write_text(path, dump_json_with_integrity(payload))
    return path


def read_job_checkpoint_metadata(storage_url: str, job_id: str, epoch: int) -> Optional[dict]:
    path = os.path.join(checkpoint_dir(storage_url, job_id, epoch), "metadata.json")
    if not storage.exists(path):
        return None
    try:
        return load_json_with_integrity(storage.read_text(path), path,
                                        _should_verify(True))
    except (json.JSONDecodeError, OSError, storage.IntegrityError):
        # torn or corrupted marker: treat as absent — the SAME predicate
        # latest_complete_checkpoint selects on, so a torn marker can never
        # be "complete" for selection yet metadata-less for restore
        return None


def evolution_mapping_path(storage_url: str, job_id: str, epoch: int) -> str:
    return os.path.join(storage_url, job_id, "checkpoints",
                        f"evolution-{epoch:07d}.json")


def write_evolution_mapping(
    storage_url: str, job_id: str, epoch: int, mapping: dict
) -> str:
    """Persist the evolution mapping (analysis/plan_diff.py diff_plans) the
    evolved plan restores ``epoch`` through. A storage sidecar — not a DB
    row — so every worker incarnation (including crash-restart loops) reads
    the SAME proven mapping; the atomic publish means a crash mid-evolve
    leaves either no mapping (restore re-validates and re-writes) or the
    complete one, never a torn half."""
    path = evolution_mapping_path(storage_url, job_id, epoch)
    storage.makedirs(os.path.dirname(path))
    storage.write_text(path, dump_json_with_integrity(mapping))
    return path


def read_evolution_mapping(
    storage_url: str, job_id: str, epoch: int
) -> Optional[dict]:
    path = evolution_mapping_path(storage_url, job_id, epoch)
    if not storage.exists(path):
        return None
    try:
        return load_json_with_integrity(storage.read_text(path), path,
                                        _should_verify(True))
    except (json.JSONDecodeError, OSError, storage.IntegrityError):
        return None


def latest_complete_checkpoint(storage_url: str, job_id: str) -> Optional[int]:
    """Newest epoch whose job-level marker PARSES (and, when verification
    is on, checksums) — the same predicate restore reads it with, so
    selection and restore can never disagree about a torn marker."""
    base = os.path.join(storage_url, job_id, "checkpoints")
    if not storage.isdir(base):
        return None
    epochs = []
    for fn in storage.listdir(base):
        if not fn.startswith("checkpoint-"):
            continue
        tag = fn.split("-", 1)[1]
        if not tag.isdigit():
            continue
        e = int(tag)
        if read_job_checkpoint_metadata(storage_url, job_id, e) is not None:
            epochs.append(e)
    return max(epochs) if epochs else None
