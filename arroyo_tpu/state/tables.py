"""Keyed / timed state tables with Parquet checkpoints.

Equivalent of crates/arroyo-state: TableManager (tables/table_manager.rs:35),
ExpiringTimeKeyTable (tables/expiring_time_key_map.rs:47), GlobalKeyedTable
(tables/global_keyed_map.rs:42), checkpoint path scheme (tables/mod.rs:20-43):

    {job}/checkpoints/checkpoint-{epoch:07}/operator-{op}/table-{name}-{subtask:03}

Restore filters Parquet files by (a) watermark-retention overlap and (b) the
restoring subtask's routing-key-range overlap, which is what makes restore at
a different parallelism (rescaling) work — same semantics as the reference
(expiring_time_key_map.rs restore path; tables/mod.rs:106-110).

In the TPU design the authoritative window state lives in HBM between
watermarks; operators mirror it into these host tables at barrier time only
(handle_checkpoint), so snapshots are taken at consistent step boundaries.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

import numpy as np

# pyarrow's IO paths have shown flaky segfaults when many engine task
# threads checkpoint while another engine restores in the same process (the
# smoke-test pattern, even with use_threads=False and a module-global lock);
# the default columnar checkpoint codec is therefore pure-numpy .npz, with
# parquet available via ``checkpoint.file-format = "parquet"`` for
# production deployments that want reference-compatible state files.
_PARQUET_IO_LOCK = threading.Lock()

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch, Schema
from ..types import TaskInfo


def _checkpoint_format() -> str:
    from ..config import config

    return config().get("checkpoint.file-format", "npz")


def write_columnar(path: str, columns: dict) -> None:
    """Write named columns to ``path`` in the configured codec. Object
    (string) columns round-trip via a pickled sidecar entry."""
    if _checkpoint_format() == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        arrays, names = [], []
        for name, col in columns.items():
            names.append(name)
            if col.dtype == object:
                arrays.append(
                    pa.array([None if v is None else str(v) for v in col], type=pa.string())
                )
            else:
                arrays.append(pa.array(col))
        with _PARQUET_IO_LOCK:
            pq.write_table(pa.table(arrays, names=names), path)
        return
    dense = {}
    objcols: dict[str, list] = {}
    for name, col in columns.items():
        if col.dtype == object:
            # keep python values as-is (ints stay ints); only unwrap numpy
            # scalars so the pickle round-trips cleanly
            objcols[name] = [v.item() if isinstance(v, np.generic) else v for v in col]
        else:
            dense[name] = col
    if objcols:
        dense["__objcols__"] = np.frombuffer(pickle.dumps(objcols), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **dense)


def read_columnar(path: str) -> dict:
    if _checkpoint_format() == "parquet":
        import pyarrow.parquet as pq

        with _PARQUET_IO_LOCK:
            table = pq.read_table(path, use_threads=False)
        cols: dict[str, np.ndarray] = {}
        for name in table.column_names:
            arr = table.column(name)
            if str(arr.type) in ("string", "large_string"):
                cols[name] = np.array(arr.to_pylist(), dtype=object)
            else:
                cols[name] = np.asarray(arr.to_numpy(zero_copy_only=False))
        return cols
    with open(path, "rb") as f:
        data = np.load(f, allow_pickle=False)
        cols = {name: data[name] for name in data.files if name != "__objcols__"}
        if "__objcols__" in data.files:
            objcols = pickle.loads(data["__objcols__"].tobytes())
            for name, vals in objcols.items():
                cols[name] = np.array(vals, dtype=object)
    return cols


def checkpoint_dir(storage_url: str, job_id: str, epoch) -> str:
    """epoch: int, or the string "final" for drained-source snapshots."""
    name = f"checkpoint-{epoch:07d}" if isinstance(epoch, int) else f"checkpoint-{epoch}"
    return os.path.join(storage_url, job_id, "checkpoints", name)


def operator_dir(storage_url: str, job_id: str, epoch, node_id: str) -> str:
    return os.path.join(checkpoint_dir(storage_url, job_id, epoch), f"operator-{node_id}")


class GlobalKeyedTable:
    """Small K/V state, full copy per checkpoint (global_keyed_map.rs:42).
    Used for source offsets, watermark-generator state, session metadata."""

    def __init__(self, name: str):
        self.name = name
        self.data: dict[Any, Any] = {}

    def get(self, key, default=None):
        return self.data.get(key, default)

    def insert(self, key, value) -> None:
        self.data[key] = value

    def delete(self, key) -> None:
        self.data.pop(key, None)

    def items(self):
        return self.data.items()

    # -- checkpoint ---------------------------------------------------------

    def write_checkpoint(self, path: str) -> dict:
        with open(path, "wb") as f:
            pickle.dump(self.data, f)
        return {"file": os.path.basename(path), "kind": "global_keyed"}

    def load_files(self, paths: Iterable[str]) -> None:
        for p in paths:
            with open(p, "rb") as f:
                self.data.update(pickle.load(f))


class ExpiringTimeKeyTable:
    """Batches bucketed by event time with retention
    (expiring_time_key_map.rs:47). Holds columnar batches; rows carry
    _timestamp and (if keyed) _key columns used for expiry and rescale."""

    def __init__(self, name: str, retention_micros: int = 0):
        self.name = name
        self.retention_micros = retention_micros
        self.batches: list[Batch] = []

    def insert(self, batch: Batch) -> None:
        if batch.num_rows:
            self.batches.append(batch)

    def replace_all(self, batches: list[Batch]) -> None:
        self.batches = [b for b in batches if b.num_rows]

    def all_batches(self) -> list[Batch]:
        return list(self.batches)

    def expire(self, watermark_micros: int) -> None:
        """Drop rows older than watermark - retention
        (expiring_time_key_map.rs:816-849)."""
        cutoff = watermark_micros - self.retention_micros
        kept = []
        for b in self.batches:
            mask = b.timestamps >= cutoff
            if mask.all():
                kept.append(b)
            elif mask.any():
                kept.append(b.filter(mask))
        self.batches = kept

    def total_rows(self) -> int:
        return sum(b.num_rows for b in self.batches)

    # -- checkpoint ---------------------------------------------------------

    def write_checkpoint(self, path: str) -> Optional[dict]:
        if not self.batches:
            return None
        merged = Batch.concat(self.batches)
        write_columnar(path, merged.columns)
        ts = merged.timestamps
        meta = {
            "file": os.path.basename(path),
            "kind": "expiring_time_key",
            "min_timestamp": int(ts.min()),
            "max_timestamp": int(ts.max()),
        }
        if KEY_FIELD in merged:
            k = merged.keys
            meta["min_key"] = int(k.min())
            meta["max_key"] = int(k.max())
        return meta

    def load_files(
        self,
        entries: Iterable[tuple[str, dict]],
        key_range: tuple[int, int],
        watermark_micros: Optional[int],
    ) -> None:
        """Restore: read files overlapping our key range & retention window."""
        cutoff = None
        if watermark_micros is not None and self.retention_micros:
            cutoff = watermark_micros - self.retention_micros
        lo, hi = key_range
        for path, meta in entries:
            if cutoff is not None and meta.get("max_timestamp", 1 << 62) < cutoff:
                continue
            if "min_key" in meta and (meta["min_key"] > hi or meta["max_key"] < lo):
                continue
            cols = read_columnar(path)
            batch = Batch(cols)
            if KEY_FIELD in batch:
                keys = batch.keys
                mask = (keys >= np.uint64(lo)) & (keys <= np.uint64(hi))
                if not mask.all():
                    batch = batch.filter(mask)
            if cutoff is not None and batch.num_rows:
                mask = batch.timestamps >= cutoff
                if not mask.all():
                    batch = batch.filter(mask)
            if batch.num_rows:
                self.batches.append(batch)


class TableManager:
    """Per-subtask state facade (tables/table_manager.rs:35)."""

    def __init__(self, task_info: TaskInfo, storage_url: str):
        self.task_info = task_info
        self.storage_url = storage_url
        self.globals: dict[str, GlobalKeyedTable] = {}
        self.expiring: dict[str, ExpiringTimeKeyTable] = {}

    def global_keyed(self, name: str) -> GlobalKeyedTable:
        if name not in self.globals:
            self.globals[name] = GlobalKeyedTable(name)
        return self.globals[name]

    def expiring_time_key(self, name: str, retention_micros: int = 0) -> ExpiringTimeKeyTable:
        if name not in self.expiring:
            self.expiring[name] = ExpiringTimeKeyTable(name, retention_micros)
        t = self.expiring[name]
        if retention_micros:
            t.retention_micros = retention_micros
        return t

    # -- checkpoint / restore ----------------------------------------------

    def checkpoint(self, epoch: int, watermark_micros: Optional[int]) -> dict:
        """Write all tables; returns subtask metadata for the engine to merge
        (reference: flusher write + OperatorCheckpointMetadata merge)."""
        ti = self.task_info
        opdir = operator_dir(self.storage_url, ti.job_id, epoch, ti.node_id)
        os.makedirs(opdir, exist_ok=True)
        sub = f"{ti.subtask_index:03d}"
        files = []
        for name, table in self.globals.items():
            meta = table.write_checkpoint(os.path.join(opdir, f"table-{name}-{sub}.bin"))
            meta["table"] = name
            files.append(meta)
        ext = "parquet" if _checkpoint_format() == "parquet" else "npz"
        for name, table in self.expiring.items():
            meta = table.write_checkpoint(os.path.join(opdir, f"table-{name}-{sub}.{ext}"))
            if meta is not None:
                meta["table"] = name
                meta["retention_micros"] = table.retention_micros
                files.append(meta)
        meta = {
            "node_id": ti.node_id,
            "subtask_index": ti.subtask_index,
            "watermark_micros": watermark_micros,
            "files": files,
        }
        with open(os.path.join(opdir, f"metadata-{sub}.json"), "w") as f:
            json.dump(meta, f)
        return meta

    def restore(self, epoch: int, table_specs: list) -> Optional[int]:
        """Load state written at ``epoch`` (possibly at different parallelism).

        Subtasks absent from the epoch snapshot (they drained before the
        barrier — e.g. a source that hit EOF) are filled from the "final"
        snapshot written at graceful finish: a drained task's state is
        constant after EOF, and everything it emitted was processed by
        downstream tasks before their epoch barriers, so its final state is
        consistent with any later epoch.
        Returns the restored watermark (min across prior subtasks), if any.
        """
        ti = self.task_info

        def read_metas(d: str) -> list:
            out = []
            if not os.path.isdir(d):
                return out
            for fn in sorted(os.listdir(d)):
                if fn.startswith("metadata-") and fn.endswith(".json"):
                    with open(os.path.join(d, fn)) as f:
                        m = json.load(f)
                    m["__dir__"] = d
                    out.append(m)
            return out

        opdir = operator_dir(self.storage_url, ti.job_id, epoch, ti.node_id)
        metas = read_metas(opdir)
        have_subtasks = {m["subtask_index"] for m in metas}
        final_dir = operator_dir(self.storage_url, ti.job_id, "final", ti.node_id)
        metas += [
            m for m in read_metas(final_dir) if m["subtask_index"] not in have_subtasks
        ]
        if not metas:
            return None
        watermarks = [m["watermark_micros"] for m in metas if m.get("watermark_micros") is not None]
        restored_wm = min(watermarks) if watermarks else None
        spec_by_name = {s.name: s for s in table_specs}
        by_table: dict[str, list[tuple[str, dict]]] = {}
        for m in metas:
            for fmeta in m["files"]:
                by_table.setdefault(fmeta["table"], []).append(
                    (os.path.join(m["__dir__"], fmeta["file"]), fmeta)
                )
        for tname, entries in by_table.items():
            spec = spec_by_name.get(tname)
            kind = entries[0][1].get("kind")
            if kind == "global_keyed":
                self.global_keyed(tname).load_files(p for p, _ in entries)
            else:
                retention = spec.retention_micros if spec else entries[0][1].get("retention_micros", 0)
                self.expiring_time_key(tname, retention).load_files(
                    entries, ti.key_range, restored_wm
                )
        return restored_wm


def compact_operator(storage_url: str, job_id: str, epoch, node_id: str) -> int:
    """Merge an operator's per-subtask state files into one file per table
    (reference: ParquetBackend::compact_operator, arroyo-state/src/parquet.rs:159
    — merges small files across checkpoints and bumps the generation).

    Snapshots here are self-contained per epoch, so compaction merges the
    per-subtask shards of one epoch. The merged file (generation 1) is
    assigned to subtask 0's metadata; other subtasks' file lists are
    cleared (their watermarks are preserved), so a later restore reads the
    data exactly once and re-shards it by routing-key range.
    Returns the number of files merged away.
    """
    opdir = operator_dir(storage_url, job_id, epoch, node_id)
    if not os.path.isdir(opdir):
        return 0
    metas = []
    for fn in sorted(os.listdir(opdir)):
        if fn.startswith("metadata-") and fn.endswith(".json"):
            with open(os.path.join(opdir, fn)) as f:
                metas.append((fn, json.load(f)))
    by_table: dict[str, list[dict]] = {}
    for _fn, m in metas:
        for fmeta in m["files"]:
            if int(fmeta.get("generation", 0)) == 0:
                by_table.setdefault(fmeta["table"], []).append(fmeta)
    merged_files: dict[str, dict] = {}
    removed = 0
    ext = "parquet" if _checkpoint_format() == "parquet" else "npz"
    for tname, fmetas in by_table.items():
        if len(fmetas) < 2:
            continue
        kind = fmetas[0]["kind"]
        out_name = f"table-{tname}-compacted-g1.{'bin' if kind == 'global_keyed' else ext}"
        out_path = os.path.join(opdir, out_name)
        if kind == "global_keyed":
            data: dict = {}
            for fm in fmetas:
                with open(os.path.join(opdir, fm["file"]), "rb") as f:
                    data.update(pickle.load(f))
            with open(out_path, "wb") as f:
                pickle.dump(data, f)
            merged = dict(fmetas[0])
        else:
            col_parts = [read_columnar(os.path.join(opdir, fm["file"])) for fm in fmetas]
            names = col_parts[0].keys()
            cols = {n: np.concatenate([p[n] for p in col_parts]) for n in names}
            write_columnar(out_path, cols)
            merged = dict(fmetas[0])
            merged["min_timestamp"] = min(fm["min_timestamp"] for fm in fmetas)
            merged["max_timestamp"] = max(fm["max_timestamp"] for fm in fmetas)
            if all("min_key" in fm for fm in fmetas):
                merged["min_key"] = min(fm["min_key"] for fm in fmetas)
                merged["max_key"] = max(fm["max_key"] for fm in fmetas)
        merged["file"] = out_name
        merged["generation"] = 1
        merged_files[tname] = merged
    if not merged_files:
        return 0
    # crash safety: merged files and rewritten metadata land BEFORE the old
    # shards are deleted — an interruption leaves a restorable epoch either
    # way (at worst both copies exist; gen-0 entries were already dropped
    # from metadata so nothing is read twice)
    for fn, m in metas:
        kept = [
            fm for fm in m["files"]
            if fm["table"] not in merged_files or int(fm.get("generation", 0)) > 0
        ]
        if m["subtask_index"] == min(mm["subtask_index"] for _f, mm in metas):
            kept.extend(merged_files.values())
        m["files"] = kept
        tmp = os.path.join(opdir, fn + ".tmp")
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, os.path.join(opdir, fn))
    for fmetas in by_table.values():
        if len(fmetas) < 2:
            continue
        for fm in fmetas:
            try:
                os.remove(os.path.join(opdir, fm["file"]))
                removed += 1
            except FileNotFoundError:
                pass
    return removed


def compact_job(storage_url: str, job_id: str, epoch) -> int:
    """Compact every operator of one completed checkpoint."""
    cdir = checkpoint_dir(storage_url, job_id, epoch)
    total = 0
    if not os.path.isdir(cdir):
        return 0
    for fn in sorted(os.listdir(cdir)):
        if fn.startswith("operator-"):
            total += compact_operator(storage_url, job_id, epoch, fn[len("operator-"):])
    return total


def cleanup_checkpoints(storage_url: str, job_id: str, min_epoch: int) -> int:
    """Delete checkpoints below ``min_epoch`` (reference
    parquet.rs:214 cleanup_operator + controller epoch GC). The "final"
    drained-source snapshot is always kept. Returns dirs removed."""
    import shutil

    base = os.path.join(storage_url, job_id, "checkpoints")
    if not os.path.isdir(base):
        return 0
    removed = 0
    for fn in sorted(os.listdir(base)):
        if not fn.startswith("checkpoint-"):
            continue
        tag = fn.split("-", 1)[1]
        if not tag.isdigit():
            continue  # "final" and friends
        if int(tag) < min_epoch:
            shutil.rmtree(os.path.join(base, fn), ignore_errors=True)
            removed += 1
    return removed


def write_job_checkpoint_metadata(
    storage_url: str, job_id: str, epoch: int, extra: Optional[dict] = None
) -> str:
    """Job-level commit marker once every subtask finished its snapshot
    (reference: controller CheckpointState -> CheckpointMetadata)."""
    d = checkpoint_dir(storage_url, job_id, epoch)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "metadata.json")
    payload = {"job_id": job_id, "epoch": epoch}
    if extra:
        payload.update(extra)
    # atomic publish: the marker's existence declares the epoch complete, so
    # a torn write must never be visible under the final name
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def read_job_checkpoint_metadata(storage_url: str, job_id: str, epoch: int) -> Optional[dict]:
    path = os.path.join(checkpoint_dir(storage_url, job_id, epoch), "metadata.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError):
        # pre-atomic-write torn file: treat as metadata-less (restore
        # validation is skipped, matching pre-validation behavior)
        return None


def latest_complete_checkpoint(storage_url: str, job_id: str) -> Optional[int]:
    base = os.path.join(storage_url, job_id, "checkpoints")
    if not os.path.isdir(base):
        return None
    epochs = []
    for fn in os.listdir(base):
        if fn.startswith("checkpoint-") and os.path.exists(os.path.join(base, fn, "metadata.json")):
            epochs.append(int(fn.split("-")[1]))
    return max(epochs) if epochs else None
