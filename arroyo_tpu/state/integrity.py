"""Checkpoint integrity verification: the restore fallback ladder's
valid-epoch selector and the offline ``fsck`` walker.

Every durable state artifact carries an integrity envelope — table files
and sidecars record ``(crc, len, algo)`` into the per-epoch manifest folded
into the job-level ``metadata.json`` commit point; JSON artifacts (the
marker itself, sidecars, evolution mappings, quarantine records) embed a
self-checksum under ``__integrity__``; spill runs, which outlive the epoch
whose manifest references them, carry a self-describing footer
(``storage.wrap_footer``). This module is the read side:

``verify_epoch``
    decides whether one epoch is a safe restore target — marker parses and
    checksums, every sidecar parses and checksums, every referenced table
    file exists and matches its envelope, every referenced spill run
    exists. Returns the list of problems (empty = valid).

``latest_valid_checkpoint``
    the fallback ladder: walk epochs newest -> oldest, QUARANTINE the
    invalid ones (``tables.quarantine_epoch`` — renamed marker, never a
    delete), return the newest epoch that verifies plus the list of
    epochs skipped and why. Sources rewind automatically: offsets live in
    the checkpointed global tables, so restoring an older epoch replays
    the gap byte-exactly.

``fsck_job``
    the offline auditor behind ``arroyo_tpu fsck`` and
    ``GET /api/v1/jobs/<id>/fsck``: walks the WHOLE chain (every epoch,
    the "final" drained snapshot, spill runs, evolution mappings, orphan
    files) and emits the shared Diagnostic model (FS-series rules).

Compaction caveat: ``compact_operator`` rewrites sidecars and deletes
merged-away shards, so the marker-folded manifest goes stale for any
operator directory holding a generation>=1 entry. The sidecars are the
authoritative envelope source from then on (they self-checksum and their
``files`` entries carry fresh envelopes); the marker manifest is only
enforced for uncompacted directories.

FS rules:

    FS001  torn epoch: directory without a parseable commit marker
    FS002  commit marker fails its integrity checksum
    FS003  quarantined epoch awaiting operator resolution
    FS004  sidecar missing, unparseable, or failing its checksum
    FS005  table file missing or failing its envelope
    FS006  referenced spill run missing or failing its footer
    FS007  evolution mapping unparseable, corrupt, or paired with the
           wrong plan hash
    FS008  orphan file no live metadata references
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable, Iterable, Optional

from ..analysis.diagnostics import Diagnostic, Severity, finish
from . import storage
from .tables import (
    QUARANTINE_MARKER,
    QUARANTINED_METADATA,
    checkpoint_dir,
    is_quarantined,
    load_json_with_integrity,
    quarantine_epoch,
)

_log = logging.getLogger("arroyo_tpu.state")


# ------------------------------------------------------------- manifest fold


def fold_integrity(subtask_metas: Iterable[dict]) -> dict:
    """Fold per-subtask checkpoint metadata (``TableManager.checkpoint``
    return values) into the per-epoch integrity manifest the job-level
    marker carries: ``{"operator-<node>/<file>": {"crc","len","algo"}}``.
    Entries without an envelope (older writers) are skipped."""
    manifest: dict[str, dict] = {}
    for m in subtask_metas:
        if not isinstance(m, dict) or "node_id" not in m:
            continue
        opd = f"operator-{m['node_id']}"
        for fm in m.get("files", ()):
            if isinstance(fm, dict) and fm.get("file") and "crc" in fm:
                manifest[f"{opd}/{fm['file']}"] = {
                    "crc": fm["crc"], "len": fm["len"],
                    "algo": fm.get("algo", "crc32")}
        sc = m.get("sidecar")
        if isinstance(sc, dict) and sc.get("file") and "crc" in sc:
            manifest[f"{opd}/{sc['file']}"] = {
                "crc": sc["crc"], "len": sc["len"],
                "algo": sc.get("algo", "crc32")}
    return manifest


# ----------------------------------------------------------- epoch walking


def _epoch_tags(storage_url: str, job_id: str) -> list[int]:
    """Numeric epoch tags present under the job's checkpoints dir."""
    base = os.path.join(storage_url, job_id, "checkpoints")
    if not storage.isdir(base):
        return []
    out = []
    for fn in storage.listdir(base):
        if fn.startswith("checkpoint-"):
            tag = fn.split("-", 1)[1]
            if tag.isdigit():
                out.append(int(tag))
    return sorted(out)


def _read_marker(storage_url: str, job_id: str, epoch: int,
                 verify: bool) -> tuple[Optional[dict], Optional[str]]:
    """(marker, problem): marker is None when missing; problem is set when
    the file exists but is torn or fails its checksum."""
    path = os.path.join(checkpoint_dir(storage_url, job_id, epoch),
                        "metadata.json")
    if not storage.exists(path):
        return None, None
    try:
        return load_json_with_integrity(
            storage.read_text(path), path, verify), None
    except Exception as e:  # noqa: BLE001 - every parse/crc failure counts
        return None, f"commit marker {path} is torn or corrupt: {e}"


def _spill_run_exists(storage_url: str, job_id: str, opd: str,
                      run: str) -> bool:
    return storage.exists(
        os.path.join(storage_url, job_id, "spill", opd, run))


def verify_epoch(storage_url: str, job_id: str, epoch: int,
                 verify_checksums: bool = True) -> list[str]:
    """Every reason ``epoch`` is NOT a safe restore target (empty list =
    valid). Existence and parseability are always checked; byte-level
    checksum verification is gated by ``verify_checksums`` (the ladder
    passes ``tables._should_verify(True)`` so ``state.integrity.verify =
    off`` keeps restores cheap; fsck always verifies)."""
    problems: list[str] = []
    marker, prob = _read_marker(storage_url, job_id, epoch, verify_checksums)
    if prob:
        return [prob]
    if marker is None:
        return [f"epoch {epoch} has no commit marker"]
    manifest = marker.get("integrity") or {}
    cdir = checkpoint_dir(storage_url, job_id, epoch)
    for node in marker.get("operators", ()):
        opd = f"operator-{node}"
        d = os.path.join(cdir, opd)
        if not storage.isdir(d):
            # a subtask that DRAINED before the barrier writes nothing for
            # the epoch — restore falls back to the "final" snapshot
            # (TableManager.restore); only a dir the manifest promised
            # artifacts for counts as missing
            if any(k.startswith(opd + "/") for k in manifest):
                problems.append(f"operator directory {opd} is missing")
            continue
        sidecars: list[tuple[str, dict]] = []
        for fn in sorted(storage.listdir(d)):
            if not (fn.startswith("metadata-") and fn.endswith(".json")):
                continue
            p = os.path.join(d, fn)
            try:
                sidecars.append((fn, load_json_with_integrity(
                    storage.read_text(p), p, verify_checksums)))
            except Exception as e:  # noqa: BLE001 - any failure disqualifies
                problems.append(f"sidecar {opd}/{fn} is torn or corrupt: {e}")
        if not sidecars and not problems:
            problems.append(f"operator {node} has no checkpoint sidecars")
        compacted = any(int(fm.get("generation", 0)) >= 1
                        for _fn, m in sidecars for fm in m.get("files", ()))
        for fn, m in sidecars:
            rel = f"{opd}/{fn}"
            env = manifest.get(rel)
            if env and verify_checksums and not compacted:
                try:
                    storage.verify_envelope(
                        storage.read_bytes(os.path.join(d, fn)), env,
                        os.path.join(d, fn))
                except storage.IntegrityError as e:
                    problems.append(f"sidecar {rel} fails the epoch "
                                    f"manifest envelope: {e.reason}")
            for fm in m.get("files", ()):
                fpath = os.path.join(d, fm["file"])
                if not storage.exists(fpath):
                    problems.append(f"table file {opd}/{fm['file']} "
                                    "is missing")
                    continue
                if verify_checksums and "crc" in fm:
                    try:
                        storage.verify_envelope(
                            storage.read_bytes(fpath), fm, fpath)
                    except storage.IntegrityError as e:
                        problems.append(f"table file {opd}/{fm['file']} "
                                        f"fails its envelope: {e.reason}")
                for run in fm.get("spill_runs", ()):
                    if not _spill_run_exists(storage_url, job_id, opd, run):
                        problems.append(
                            f"spill run {opd}/{run} referenced by table "
                            f"{fm.get('table')!r} is missing")
    return problems


# --------------------------------------------------------- fallback ladder


def latest_valid_checkpoint(
    storage_url: str, job_id: str,
    on_quarantine: Optional[Callable[[int, str], None]] = None,
) -> tuple[Optional[int], list[dict]]:
    """The restore fallback ladder. Walk complete-looking epochs newest ->
    oldest; an epoch that fails ``verify_epoch`` is QUARANTINED (marker
    preserved under ``metadata.json.quarantined`` — never deleted; GC and
    subsume refuse it until an operator resolves it) and the walk falls
    back to the next-older epoch. Returns ``(epoch, skipped)`` where
    ``skipped`` is ``[{"epoch", "reason"}, ...]`` for the RESTORE_FELL_BACK
    event — empty when the newest epoch verified first try. ``epoch`` is
    None when no valid epoch remains (fresh start).

    ``on_quarantine(epoch, reason)`` fires after each quarantine so callers
    can emit CHECKPOINT_QUARANTINED with storage state already consistent.
    """
    from .tables import _should_verify

    verify_checksums = _should_verify(True)
    skipped: list[dict] = []
    for epoch in reversed(_epoch_tags(storage_url, job_id)):
        if is_quarantined(storage_url, job_id, epoch):
            continue
        marker_path = os.path.join(
            checkpoint_dir(storage_url, job_id, epoch), "metadata.json")
        if not storage.exists(marker_path):
            continue  # torn epoch: invisible to restore, subsume owns it
        problems = verify_epoch(storage_url, job_id, epoch, verify_checksums)
        if not problems:
            return epoch, skipped
        reason = "; ".join(problems[:5])
        quarantine_epoch(storage_url, job_id, epoch, reason)
        skipped.append({"epoch": epoch, "reason": reason})
        if on_quarantine is not None:
            on_quarantine(epoch, reason)
    return None, skipped


# ------------------------------------------------------------------- fsck


def _fsck_epoch(storage_url: str, job_id: str, epoch: int,
                diags: list[Diagnostic]) -> None:
    site = f"{job_id}/checkpoints/checkpoint-{epoch:07d}"
    if is_quarantined(storage_url, job_id, epoch):
        diags.append(Diagnostic(
            "FS003", Severity.WARNING, site,
            f"epoch {epoch} is quarantined and awaits operator resolution",
            hint="inspect metadata.json.quarantined + quarantine.json; "
                 "delete the directory (or restore the marker) to resolve"))
        return
    marker, prob = _read_marker(storage_url, job_id, epoch, verify=True)
    if prob:
        diags.append(Diagnostic(
            "FS002", Severity.ERROR, site, prob,
            hint="quarantine-and-fall-back will skip this epoch on the "
                 "next restore; resolve or delete it after forensics"))
        return
    if marker is None:
        diags.append(Diagnostic(
            "FS001", Severity.WARNING, site,
            f"epoch {epoch} has no commit marker (torn mid-checkpoint)",
            hint="harmless: invisible to restore; the controller watchdog "
                 "subsumes torn epochs automatically"))
        return
    for p in verify_epoch(storage_url, job_id, epoch, verify_checksums=True):
        rule = ("FS004" if "sidecar" in p
                else "FS006" if "spill run" in p
                else "FS005")
        diags.append(Diagnostic(
            rule, Severity.ERROR, site, p,
            hint="restore would quarantine this epoch and fall back"))


def _fsck_final(storage_url: str, job_id: str,
                diags: list[Diagnostic]) -> None:
    """The "final" drained-source snapshot dir verifies like an epoch's
    operator dirs but has no commit marker of its own."""
    cdir = checkpoint_dir(storage_url, job_id, "final")
    if not storage.isdir(cdir):
        return
    site = f"{job_id}/checkpoints/checkpoint-final"
    for opd in sorted(storage.listdir(cdir)):
        d = os.path.join(cdir, opd)
        if not opd.startswith("operator-") or not storage.isdir(d):
            continue
        for fn in sorted(storage.listdir(d)):
            if not (fn.startswith("metadata-") and fn.endswith(".json")):
                continue
            p = os.path.join(d, fn)
            try:
                m = load_json_with_integrity(storage.read_text(p), p, True)
            except Exception as e:  # noqa: BLE001 - report, keep walking
                diags.append(Diagnostic(
                    "FS004", Severity.ERROR, site,
                    f"sidecar {opd}/{fn} is torn or corrupt: {e}"))
                continue
            for fm in m.get("files", ()):
                fpath = os.path.join(d, fm["file"])
                if not storage.exists(fpath):
                    diags.append(Diagnostic(
                        "FS005", Severity.ERROR, site,
                        f"table file {opd}/{fm['file']} is missing"))
                elif "crc" in fm:
                    try:
                        storage.verify_envelope(
                            storage.read_bytes(fpath), fm, fpath)
                    except storage.IntegrityError as e:
                        diags.append(Diagnostic(
                            "FS005", Severity.ERROR, site,
                            f"table file {opd}/{fm['file']} fails its "
                            f"envelope: {e.reason}"))


def _fsck_evolutions(storage_url: str, job_id: str, epochs: list[int],
                     diags: list[Diagnostic]) -> None:
    base = os.path.join(storage_url, job_id, "checkpoints")
    if not storage.isdir(base):
        return
    for fn in sorted(storage.listdir(base)):
        if not (fn.startswith("evolution-") and fn.endswith(".json")):
            continue
        site = f"{job_id}/checkpoints/{fn}"
        tag = fn[len("evolution-"):-len(".json")]
        p = os.path.join(base, fn)
        try:
            mapping = load_json_with_integrity(storage.read_text(p), p, True)
        except Exception as e:  # noqa: BLE001 - report, keep walking
            diags.append(Diagnostic(
                "FS007", Severity.ERROR, site,
                f"evolution mapping is torn or corrupt: {e}",
                hint="re-run the evolve API so the plan-diff pass rewrites "
                     "the proven mapping"))
            continue
        if not tag.isdigit():
            diags.append(Diagnostic(
                "FS007", Severity.WARNING, site,
                f"evolution mapping has a non-numeric epoch tag {tag!r}"))
            continue
        epoch = int(tag)
        if epoch not in epochs:
            diags.append(Diagnostic(
                "FS008", Severity.WARNING, site,
                f"evolution mapping references epoch {epoch} which has no "
                "checkpoint directory (orphan)",
                hint="safe to delete after confirming no restore targets it"))
            continue
        marker, _prob = _read_marker(storage_url, job_id, epoch, verify=False)
        meta_hash = (marker or {}).get("plan_hash")
        old_hash = mapping.get("old_plan_hash")
        if meta_hash and old_hash and meta_hash != old_hash:
            diags.append(Diagnostic(
                "FS007", Severity.ERROR, site,
                f"evolution mapping pairs old plan {old_hash} but epoch "
                f"{epoch}'s marker records plan {meta_hash} — the mapping "
                "was proven for a different plan pair",
                hint="restore through this mapping would misread state; "
                     "re-run the evolve API against the actual checkpoint"))


def _fsck_orphans(storage_url: str, job_id: str, epochs: list[int],
                  diags: list[Diagnostic]) -> None:
    """FS008: files no live metadata references. Table-file orphans are
    torn-compaction leftovers ``compact_operator`` finishes deleting;
    spill-run orphans below the newest complete epoch are
    ``cleanup_spill_runs`` targets. Both are WARNING — owned by GC, not
    data loss."""
    known_epoch_files = {"metadata.json", QUARANTINE_MARKER,
                         QUARANTINED_METADATA}
    referenced_runs: set[tuple[str, str]] = set()
    newest_complete = None
    for epoch in epochs:
        cdir = checkpoint_dir(storage_url, job_id, epoch)
        site = f"{job_id}/checkpoints/checkpoint-{epoch:07d}"
        marker, _prob = _read_marker(storage_url, job_id, epoch, verify=False)
        if marker is not None:
            newest_complete = epoch
        for fn in sorted(storage.listdir(cdir)):
            d = os.path.join(cdir, fn)
            if storage.isdir(d):
                if not fn.startswith("operator-"):
                    diags.append(Diagnostic(
                        "FS008", Severity.WARNING, site,
                        f"unexpected directory {fn!r} in the epoch dir"))
                    continue
                sidecar_refs: set[str] = set()
                for sfn in storage.listdir(d):
                    if not (sfn.startswith("metadata-")
                            and sfn.endswith(".json")):
                        continue
                    try:
                        m = json.loads(
                            storage.read_text(os.path.join(d, sfn)))
                    except Exception:  # noqa: BLE001 - FS004 reported it
                        continue
                    for fm in m.get("files", ()):
                        sidecar_refs.add(fm.get("file", ""))
                        for run in fm.get("spill_runs", ()):
                            referenced_runs.add((fn, run))
                for sfn in sorted(storage.listdir(d)):
                    if (sfn.startswith("table-")
                            and sfn not in sidecar_refs):
                        diags.append(Diagnostic(
                            "FS008", Severity.WARNING, site,
                            f"table file {fn}/{sfn} is referenced by no "
                            "sidecar (torn-compaction leftover)",
                            hint="compact_operator finishes the cleanup on "
                                 "its next pass"))
            elif fn not in known_epoch_files:
                diags.append(Diagnostic(
                    "FS008", Severity.WARNING, site,
                    f"unexpected file {fn!r} in the epoch dir"))
    spill_base = os.path.join(storage_url, job_id, "spill")
    if not storage.isdir(spill_base):
        return
    from .spill import _RUN_NAME_RE

    for opd in sorted(storage.listdir(spill_base)):
        d = os.path.join(spill_base, opd)
        if not storage.isdir(d):
            continue
        for fn in sorted(storage.listdir(d)):
            m = _RUN_NAME_RE.match(fn)
            if m is None:
                continue
            run_epoch = int(m.group(2))
            if (newest_complete is not None and run_epoch >= newest_complete):
                continue  # fresh post-checkpoint run; next manifest owns it
            if (opd, fn) not in referenced_runs:
                diags.append(Diagnostic(
                    "FS008", Severity.WARNING, f"{job_id}/spill/{opd}",
                    f"spill run {fn} is referenced by no checkpoint "
                    "manifest (GC target)",
                    hint="cleanup_spill_runs removes it on the next GC "
                         "cycle"))


def _fsck_spill_footers(storage_url: str, job_id: str,
                        diags: list[Diagnostic]) -> None:
    """FS006: every live spill run's self-describing footer must verify
    (runs outlive epochs, so their integrity rides in the file itself)."""
    from .spill import _RUN_NAME_RE

    spill_base = os.path.join(storage_url, job_id, "spill")
    if not storage.isdir(spill_base):
        return
    for opd in sorted(storage.listdir(spill_base)):
        d = os.path.join(spill_base, opd)
        if not storage.isdir(d):
            continue
        for fn in sorted(storage.listdir(d)):
            if _RUN_NAME_RE.match(fn) is None:
                continue
            p = os.path.join(d, fn)
            try:
                storage.unwrap_footer(storage.read_bytes(p), p, verify=True)
            except storage.IntegrityError as e:
                diags.append(Diagnostic(
                    "FS006", Severity.ERROR, f"{job_id}/spill/{opd}",
                    f"spill run {fn} fails its integrity footer: "
                    f"{e.reason}",
                    hint="a probe read would fail here; the worker set "
                         "restores from the checkpoint instead"))


def fsck_job(storage_url: str, job_id: str) -> list[Diagnostic]:
    """Walk one job's whole durable-state chain offline and report every
    integrity finding as a Diagnostic (FS-series rules; deterministic
    order via ``finish``). ERROR findings mean a restore would quarantine
    and fall back; WARNINGs are GC-owned debris or operator-pending
    quarantines. Checksum verification is ALWAYS on here regardless of
    ``state.integrity.verify`` — fsck exists to look."""
    diags: list[Diagnostic] = []
    epochs = _epoch_tags(storage_url, job_id)
    if not epochs and not storage.isdir(
            os.path.join(storage_url, job_id, "checkpoints")):
        diags.append(Diagnostic(
            "FS001", Severity.INFO, f"{job_id}/checkpoints",
            "job has no checkpoints directory (nothing to verify)"))
        return finish(diags)
    for epoch in epochs:
        _fsck_epoch(storage_url, job_id, epoch, diags)
    _fsck_final(storage_url, job_id, diags)
    _fsck_evolutions(storage_url, job_id, epochs, diags)
    _fsck_orphans(storage_url, job_id, epochs, diags)
    _fsck_spill_footers(storage_url, job_id, diags)
    return finish(diags)
