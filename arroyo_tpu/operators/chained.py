"""Operator chaining: fuse Forward-edge neighbors into one task.

Equivalent of the reference's ChainingOptimizer + ChainedOperator
(crates/arroyo-datastream/src/optimizers.rs:40-105 — merge when Forward edge,
equal parallelism, single in/out, not source/sink — and
crates/arroyo-operator/src/operator.rs:424-428 ChainedOperator with
ChainedCollector threading output of op N into op N+1 in place :370-422).

On this engine a chain collapses per-batch queue hops and thread handoffs —
the host-side analog of XLA op fusion, and a direct throughput lever since
every hop costs a bounded-queue put/get plus a GIL switch. A chained run
marked compilable at plan time additionally runs its data path as ONE
jitted call per micro-batch (engine/segment.py whole-segment compilation);
this class stays the interpreted ground truth the compiled path verifies
against and falls back to.

Interplay with micro-batch coalescing (operators/collector.py): member-to-
member hops are plain in-process calls, so there is deliberately NO
coalescing buffer between chain members — only the chain's terminal
collector (the task's real Collector) coalesces, right where the queue/
data-plane overhead being amortized actually lives. Signal flushing is
inherited from that terminal collector: a watermark threaded through
ChainCollector.broadcast ends at Collector.broadcast, which flushes pending
rows ahead of the signal."""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ..engine.engine import construct_operator, register_operator
from ..graph import OpName
from ..operators.base import Operator, OperatorContext
from ..types import Signal, SignalKind, Watermark


class PrefixedTables:
    """Namespaces one chain member's state tables inside the shared
    TableManager so two members' same-named tables cannot collide."""

    def __init__(self, inner, prefix: str):
        self._inner = inner
        self._prefix = prefix

    def global_keyed(self, name: str):
        return self._inner.global_keyed(self._prefix + name)

    def expiring_time_key(self, name: str, retention_micros: int = 0):
        return self._inner.expiring_time_key(self._prefix + name, retention_micros)

    def __getattr__(self, item):
        return getattr(self._inner, item)


class ChainCollector:
    """Collector handed to chain member i: data flows into member i+1's
    process_batch in place; watermark broadcasts thread through member i+1's
    handle_watermark (so holds/adjustments still apply); other signals pass
    through untouched (barriers originate in the task loop, not members)."""

    def __init__(self, op: Operator, ctx: OperatorContext, next_collector):
        self.op = op
        self.ctx = ctx
        self.next = next_collector

    def collect(self, batch) -> None:
        self.op.process_batch(batch, self.ctx, self.next)

    def broadcast(self, signal: Signal) -> None:
        if signal.kind == SignalKind.WATERMARK:
            self.ctx.last_watermark = signal.watermark
            out = self.op.handle_watermark(signal.watermark, self.ctx, self.next)
            if out is not None:
                self.next.broadcast(Signal.watermark_of(out))
        else:
            self.next.broadcast(signal)


class ChainedOperator(Operator):
    """config: members = [(op_name_value, member_config), ...] in data order."""

    def __init__(self, cfg: dict):
        self.members: list[Operator] = [
            construct_operator(OpName(op), c) for op, c in cfg["members"]
        ]
        # raw member (op, config) pairs + the optimizer's plan-time
        # compilability marking: engine/segment.py keys its compile cache
        # off these and traces the marked prefix into one jitted call
        self.cfg_members: list = list(cfg["members"])
        self.compile_marking: Optional[dict] = cfg.get("compile")
        # plan-time "not compilable: <reason>" (optimizer.chain_graph):
        # runner_for copies it into the task metrics so top/explain can
        # render the reject next to the [compiled] marker
        self.compile_reject: Optional[str] = cfg.get("compile_reject")
        self._ctxs: Optional[list[OperatorContext]] = None
        self._cols = None
        # only members that declared a tick interval get ticked: the chain
        # ticks at the MINIMUM member interval, and waking every member at
        # the fastest member's cadence is wasted hot-loop work
        self._tickers = [i for i, m in enumerate(self.members)
                         if m.tick_interval_micros() is not None]

    def name(self) -> str:
        return "+".join(m.name() for m in self.members)

    @property
    def late_rows(self) -> int:
        """Chain-wide late/expired-row drops (obs/profile.py exports this
        per task, so a chain reports its members' sum)."""
        return sum(int(getattr(m, "late_rows", 0) or 0) for m in self.members)

    def state_sizes(self) -> dict[str, tuple[int, int]]:
        """Members' live-store gauges, namespaced like their state tables
        (PrefixedTables uses the same ``c{i}.`` prefix)."""
        out: dict[str, tuple[int, int]] = {}
        for i, m in enumerate(self.members):
            fn = getattr(m, "state_sizes", None)
            if fn is not None:
                for name, v in fn().items():
                    out[f"c{i}.{name}"] = v
        return out

    def spill_stats(self):
        """Members' tiered-state counters folded into one chain-level
        block (state/spill.py merge: counters sum, histograms add)."""
        from ..state.spill import merge_spill_stats

        return merge_spill_stats(
            [fn() for m in self.members
             for fn in (getattr(m, "spill_stats", None),) if fn is not None])

    def mesh_stats(self):
        """Fused-mesh residency of the chain's window member, if any (the
        sharded aggregate lives on exactly one member — obs/profile.py
        exports this as the arroyo_mesh_* series)."""
        for m in self.members:
            fn = getattr(m, "mesh_stats", None)
            if fn is not None:
                stats = fn()
                if stats is not None:
                    return stats
        return None

    def tables(self):
        specs = []
        for i, m in enumerate(self.members):
            for t in m.tables():
                specs.append(replace(t, name=f"c{i}.{t.name}"))
        return specs

    def on_start(self, ctx: OperatorContext) -> None:
        # collectors are rebuilt on first process_batch (on_start has none);
        # member on_start only needs the namespaced tables
        self._setup_ctx_only(ctx)
        for i, m in enumerate(self.members):
            m.on_start(self._ctxs[i])

    def _setup_ctx_only(self, ctx: OperatorContext) -> None:
        if self._ctxs is None:
            self._ctxs = [
                OperatorContext(
                    ctx.task_info,
                    ctx.out_schema if i == len(self.members) - 1 else None,
                    PrefixedTables(ctx.table_manager, f"c{i}."),
                    in_edge_of_input=ctx._in_edge_of_input,
                )
                for i in range(len(self.members))
            ]

    def _chain_cols(self, collector):
        if self._cols is None or self._outer is not collector:
            cols = [None] * len(self.members)
            nxt = collector
            for i in range(len(self.members) - 1, -1, -1):
                cols[i] = nxt
                if i > 0:
                    nxt = ChainCollector(self.members[i], self._ctxs[i], nxt)
            self._cols = cols
            self._outer = collector
        return self._cols

    def process_batch(self, batch, ctx, collector, input_index=0) -> None:
        cols = self._chain_cols(collector)
        self.members[0].process_batch(batch, self._ctxs[0], cols[0], input_index=input_index)

    def handle_watermark(self, watermark: Watermark, ctx, collector) -> Optional[Watermark]:
        cols = self._chain_cols(collector)
        w: Optional[Watermark] = watermark
        for i, m in enumerate(self.members):
            self._ctxs[i].last_watermark = w
            w = m.handle_watermark(w, self._ctxs[i], cols[i])
            if w is None:
                return None
        return w

    def handle_checkpoint(self, barrier, ctx, collector) -> None:
        cols = self._chain_cols(collector)
        for i, m in enumerate(self.members):
            m.handle_checkpoint(barrier, self._ctxs[i], cols[i])

    def handle_commit(self, epoch: int, ctx) -> None:
        for i, m in enumerate(self.members):
            m.handle_commit(epoch, self._ctxs[i])

    def is_committing(self) -> bool:
        return any(m.is_committing() for m in self.members)

    def tick_interval_micros(self) -> Optional[int]:
        ticks = [t for m in self.members if (t := m.tick_interval_micros()) is not None]
        return min(ticks) if ticks else None

    def handle_tick(self, ctx, collector) -> None:
        cols = self._chain_cols(collector)
        for i in self._tickers:
            self.members[i].handle_tick(self._ctxs[i], cols[i])

    def on_close(self, ctx, collector) -> None:
        cols = self._chain_cols(collector)
        for i, m in enumerate(self.members):
            m.on_close(self._ctxs[i], cols[i])


@register_operator(OpName.CHAINED)
def _make_chained(cfg: dict):
    return ChainedOperator(cfg)
