"""Updating (non-windowed) aggregate with retractions and TTL.

Reference behavior: crates/arroyo-worker/src/arrow/incremental_aggregator.rs
:199 — keyed incremental accumulators (UpdatingCache with TTL + generation);
on the flush interval emit retract/append pairs for keys whose value changed
(:638-700, identical-value updates suppressed :649-652); TTL eviction emits
retractions (:683+). Updating rows are tagged via an ``_updating_meta``
struct with ``is_retract`` (arroyo-rpc/src/lib.rs:254-267); here the flat
``_is_retract`` boolean column plays that role end-to-end (formats serialize
it Debezium-style at sinks).

COUNT(DISTINCT) accumulates a per-value multiplicity map per key (kind
"collect"), which inverts exactly under retractions.

Input may itself be updating (downstream of an updating join): retractions
are applied with invertible accumulators (sum/count/avg); min/max over an
updating input would need per-key re-reduce and is rejected at plan time.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec, persist_mark, restore_marks
from ..windows.tumbling import acc_plan, dtype_of_from_config

IS_RETRACT_FIELD = "_is_retract"


class _KeyState:
    __slots__ = ("accs", "count", "emitted", "last_update")

    def __init__(self, accs: list, count: int, last_update: int):
        self.accs = accs
        self.count = count  # live rows backing this key (0 -> delete)
        self.emitted: Optional[tuple] = None  # last appended output values
        self.last_update = last_update  # event-time micros for TTL


def _pack_key_state(st: _KeyState, kv) -> tuple:
    """Spill payload for one key (state/spill.py pack contract: the event
    time rides at index -1 so the annex can zone-map runs without
    unpickling)."""
    return (tuple(st.accs), st.count, st.emitted, kv, st.last_update)


def _unpack_key_state(packed: tuple) -> tuple[_KeyState, Optional[tuple]]:
    accs, count, emitted, kv, last_update = packed
    st = _KeyState(list(accs), int(count), int(last_update))
    st.emitted = emitted
    return st, (tuple(kv) if kv is not None else None)


class UpdatingAggregate(Operator):
    """config: key_fields, aggregates: [(name, kind, Expr|None)],
    flush_interval_micros (default 1s), ttl_micros (default 1 day),
    input_dtype_of."""

    def __init__(self, cfg: dict):
        from ..config import config

        self.key_fields: list[str] = list(cfg.get("key_fields", ()))
        self.aggregates = cfg["aggregates"]
        dtype_of = dtype_of_from_config(cfg)
        self.acc_kinds, self.acc_dtypes, self.acc_inputs = acc_plan(self.aggregates, dtype_of)
        self.flush_interval = int(cfg.get("flush_interval_micros", 1_000_000))
        self.ttl = int(cfg.get("ttl_micros", 24 * 3600 * 1_000_000))
        self.state: dict[int, _KeyState] = {}
        self.key_values: dict[int, tuple] = {}
        self.updated: set[int] = set()  # state: ephemeral — flushed empty at every barrier (handle_checkpoint flushes first); rebuilt by replay
        # high-water event time: stamps emitted rows and anchors TTL
        # eviction; checkpointed into the "m" global table at every barrier
        # and restored, so replayed emissions carry the same timestamps the
        # original run emitted
        self.max_event_time: int = 0
        # device lowering (sum/count/avg — the invertible kinds): running
        # accumulators live in HBM as signed scatter lanes (append +v,
        # retract -v; the count rides as a ±1 sum lane), so the per-batch
        # hot path is one fused device step with NO per-key Python loop.
        # The flush gathers only the touched keys' slots — a bounded gather
        # once per interval, never in the batch loop. min/max stay host-side
        # (non-invertible; reference rejects them over updating inputs too).
        backend = cfg.get("backend") or (
            "jax" if config().get("device.enabled") else "numpy"
        )
        # tiered state (state/spill.py): with spilling on, the keyed
        # accumulator map runs on the host path — the hot working set stays
        # in self.state and cold hash-range partitions live in the annex.
        # (The device store is capacity-bound HBM; larger-than-RAM keyspaces
        # are exactly the case it cannot hold.)
        from ..state.spill import spill_enabled

        self._spill = spill_enabled()
        self._annex = None  # KeyedSpillAnnex, built in on_start when spilling
        self.device_mode = (
            backend == "jax"
            and all(k in ("sum", "count") for k in self.acc_kinds)
            and not self._spill
        )
        # the device store always carries a count lane (±1 per row): it is
        # the liveness/ordering ground truth even when the SQL has no
        # count(*) — sum-only configs would otherwise misread "sums to
        # zero" as "key dead"
        self._count_lane = next(
            (i for i, k in enumerate(self.acc_kinds) if k == "count"), None)
        self._synthetic_count = self.device_mode and self._count_lane is None
        if self._synthetic_count:
            self._count_lane = len(self.acc_kinds)
        self._dev = None  # SlotAggregator, built lazily
        self._dead_since_compact = 0
        self._last_update: dict[int, int] = {}  # key hash -> event time
        self._emitted: dict[int, tuple] = {}  # key hash -> last appended vals

    # ------------------------------------------------------------------

    def tables(self):
        # "m" holds the event-time high-water mark (global: persists even
        # when the key snapshot is empty, where a column on "s" would be
        # silently dropped with the 0-row batch); "s__spill" holds the
        # tiered-state manifest — spilled runs by reference, never
        # re-uploaded (state/spill.py; written only when spilling is on)
        return [TableSpec("s", "expiring_time_key", retention_micros=self.ttl),
                TableSpec("m", "global_keyed"),
                TableSpec("s__spill", "global_keyed")]

    def tick_interval_micros(self):
        return self.flush_interval

    def on_start(self, ctx):
        if self._spill:
            from ..state.spill import KeyedSpillAnnex, restore_manifest

            self._annex = KeyedSpillAnnex(
                ctx.task_info, ctx.table_manager.storage_url, "s")
            self._annex.adopt(restore_manifest(ctx, "s__spill"))
        else:
            from ..state.spill import require_spill_for_manifest

            # a checkpoint taken WITH spilling holds most of the keyspace
            # in run files; restoring hot rows alone would silently
            # corrupt — fail the restore instead
            require_spill_for_manifest(ctx, "s__spill")
        # event-time high-water mark: stamps emitted rows and anchors TTL
        # eviction, so replayed emissions carry the original timestamps.
        # DATA-derived and therefore per-subtask (unlike the watermark-
        # aligned window boundaries): restore OUR OWN entry so another
        # subtask's higher mark cannot contaminate this one's emission
        # timestamps; fall back to the max merge only when our entry is
        # absent (restore at a different parallelism)
        own = ctx.table_manager.global_keyed("m").get(
            ctx.task_info.subtask_index)
        if own is not None:
            self.max_event_time = max(self.max_event_time, own)
        else:
            marks = restore_marks(ctx, "m")
            if marks:
                self.max_event_time = max(self.max_event_time, max(marks))
        tbl = ctx.table_manager.expiring_time_key("s", self.ttl)
        batches = tbl.all_batches()
        if batches and self.device_mode:
            self._restore_device(Batch.concat(batches))
            tbl.replace_all([])
            return
        if batches:
            b = Batch.concat(batches)
            hashes = b.keys.astype(np.uint64).view(np.int64)
            key_cols = [b[f] for f in self.key_fields]
            emitted_mask = b["__has_emitted"].astype(bool) if "__has_emitted" in b else None
            n_agg = len(self.aggregates)
            count_i = next(
                (i for i, k in enumerate(self.acc_kinds) if k == "count"), None)
            import json as _json

            for j in range(b.num_rows):
                h = int(hashes[j])
                accs = [
                    {p[0]: p[1] for p in _json.loads(b[f"__acc_{i}"][j])}
                    if self.acc_kinds[i] == "collect"
                    else d.type(b[f"__acc_{i}"][j])
                    for i, d in enumerate(self.acc_dtypes)
                ]
                if "__count" in b:
                    count = int(b["__count"][j])
                elif count_i is not None:
                    count = int(accs[count_i])  # device-mode checkpoint layout
                else:
                    count = 1
                st = _KeyState(accs, count, int(b.timestamps[j]))
                if emitted_mask is not None and emitted_mask[j]:
                    st.emitted = tuple(
                        b[f"__emitted_{i}"][j] for i in range(n_agg)
                    )
                self.state[h] = st
                if self.key_fields:
                    self.key_values[h] = tuple(c[j] for c in key_cols)
            tbl.replace_all([])

    # ------------------------------------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        ts = batch.timestamps
        self.max_event_time = max(self.max_event_time, int(ts.max()))
        if KEY_FIELD in batch:
            hashes = batch.keys.astype(np.uint64).view(np.int64)
        else:
            hashes = np.zeros(n, dtype=np.int64)
        retracts = (
            np.asarray(batch[IS_RETRACT_FIELD], dtype=bool)
            if IS_RETRACT_FIELD in batch
            else np.zeros(n, dtype=bool)
        )
        if retracts.any():
            for kind in self.acc_kinds:
                # collect = COUNT(DISTINCT)'s per-value multiplicity map,
                # which inverts exactly (append +1 / retract -1 per value)
                if kind not in ("sum", "count", "collect"):
                    raise ValueError(
                        f"updating aggregate over an updating input requires "
                        f"invertible accumulators; {kind} is not"
                    )
        # accumulate values per row, then fold per unique key
        vals = []
        for inp, dt, kind in zip(self.acc_inputs, self.acc_dtypes, self.acc_kinds):
            if inp is None:
                vals.append(np.ones(n, dtype=dt))
            elif kind == "collect":
                # raw distinct-candidate values (any hashable scalar type)
                v = np.asarray(eval_expr(inp, batch.columns, n))
                vals.append(v if v.dtype == object else v.astype(object))
            else:
                vals.append(np.asarray(eval_expr(inp, batch.columns, n)).astype(dt))
        if self.device_mode:
            self._process_device(hashes, ts, retracts, vals, batch)
            return
        if self._annex is not None:
            self._ensure_hot(hashes)
        order = np.argsort(hashes, kind="stable")
        k_s = hashes[order]
        r_s = retracts[order]
        t_s = np.asarray(ts)[order]
        v_s = [v[order] for v in vals]
        brk = np.ones(n, dtype=bool)
        brk[1:] = k_s[1:] != k_s[:-1]
        starts = np.flatnonzero(brk)
        ends = np.append(starts[1:], n)
        if self.key_fields:
            cols = [np.asarray(batch[f])[order] for f in self.key_fields]
            for si in starts:
                h = int(k_s[si])
                if h not in self.key_values:
                    self.key_values[h] = tuple(c[si] for c in cols)
        for si, ei in zip(starts, ends):
            h = int(k_s[si])
            st = self.state.get(h)
            last_ts = int(t_s[ei - 1])
            if st is None:
                st = _KeyState(
                    [self._identity(i) for i in range(len(self.acc_kinds))], 0, last_ts
                )
                self.state[h] = st
            st.last_update = max(st.last_update, last_ts)
            seg_r = r_s[si:ei]
            n_app = int((~seg_r).sum())
            n_ret = int(seg_r.sum())
            st.count += n_app - n_ret
            if st.count < 0:
                raise RuntimeError(
                    "retract without matching append for key (updating stream "
                    "ordering violation)"
                )
            for i, kind in enumerate(self.acc_kinds):
                seg = v_s[i][si:ei]
                app = seg[~seg_r]
                ret = seg[seg_r]
                cur = st.accs[i]
                if kind == "collect":
                    # per-value multiplicity map: distinct set = live keys
                    m: dict = cur
                    for v in app:
                        v = v.item() if isinstance(v, np.generic) else v
                        m[v] = m.get(v, 0) + 1
                    for v in ret:
                        v = v.item() if isinstance(v, np.generic) else v
                        c = m.get(v, 0) - 1
                        if c <= 0:
                            m.pop(v, None)
                        else:
                            m[v] = c
                    continue
                if kind in ("sum", "count"):
                    cur = cur + app.sum() - ret.sum()
                elif kind == "min":
                    cur = min(cur, app.min()) if len(app) else cur
                else:
                    cur = max(cur, app.max()) if len(app) else cur
                st.accs[i] = self.acc_dtypes[i].type(cur)
            self.updated.add(h)
        if self._annex is not None:
            self._maybe_spill()

    # --------------------------------------------------------- tiered state

    def _ensure_hot(self, hashes: np.ndarray) -> None:
        """Promote every batch key with a cold (spilled) copy into the hot
        dict before the fold loop touches it — the probe is one bloom/zone
        pruned pass per batch, never per key."""
        annex = self._annex
        uniq = np.unique(hashes)
        annex.touch(uniq)
        if not annex.has_runs():
            return
        missing = [h for h in uniq.tolist() if h not in self.state]
        if not missing:
            return
        for h, packed in sorted(annex.lookup_many(missing).items()):
            st, kv = _unpack_key_state(packed)
            self.state[h] = st
            if kv is not None:
                self.key_values[h] = kv

    def _entry_nbytes(self, h: int, st: _KeyState) -> int:
        """Resident-bytes floor for one key (same role as the join's
        per-row estimate: feeds arroyo_state_bytes AND the spill budget)."""
        import sys as _sys

        b = 160  # dict slots + _KeyState object overhead
        for a in st.accs:
            b += (_sys.getsizeof(a) + 64 * len(a)) if isinstance(a, dict) \
                else 32
        if st.emitted is not None:
            b += 56 + 32 * len(st.emitted)
        kv = self.key_values.get(h)
        if kv is not None:
            b += 56 + sum(_sys.getsizeof(v) for v in kv)
        return b

    def _estimate_state_bytes(self) -> tuple[int, float]:
        """(estimated resident bytes, per-entry average), sampled over up
        to 64 entries so the per-batch budget check stays O(1)."""
        import itertools as _it

        n = len(self.state)
        if not n:
            return 0, 0.0
        tot = cnt = 0
        for h, st in _it.islice(self.state.items(), 64):
            tot += self._entry_nbytes(h, st)
            cnt += 1
        per = tot / cnt
        return int(per * n), per

    def state_sizes(self) -> dict[str, tuple[int, int]]:
        """Live resident-state gauge for the host path (between barriers
        the "s" table lags the in-memory map; device mode keeps the
        as-of-barrier table view)."""
        if self.device_mode:
            return {}
        est, _per = self._estimate_state_bytes()
        return {"s": (len(self.state), est)}

    def spill_stats(self) -> Optional[dict]:
        annex = self._annex
        if annex is None:
            return None
        cold = annex.cold_partitions()
        return {"bytes_total": annex.stats.bytes_total,
                "hot": max(0, annex.local_partitions() - cold), "cold": cold,
                "probe_files": annex.stats.probe_files}

    def _maybe_spill(self) -> None:
        """Budget enforcement: when resident state passes
        ``state.spill.budget-bytes``, spill the coldest partitions (the
        annex's deterministic clock-LRU) down to the low-water mark."""
        from ..config import config
        from ..state.spill import spill_budget_bytes

        annex = self._annex
        if annex is None or not self.state:
            return
        budget = spill_budget_bytes()
        est_total, per_entry = self._estimate_state_bytes()
        if est_total <= budget:
            return
        target = budget * float(config().get("state.spill.headroom", 0.75))
        excess = int((est_total - target) / max(per_entry, 1.0)) + 1
        # keys with pending un-flushed updates are spillable too (the next
        # _flush promotes them back): budget enforcement must not depend
        # on the watermark cadence that clears the updated set. The clock
        # LRU keeps their (just-touched) partitions at the back of the
        # victim line anyway.
        hot_by_p: dict[int, list[int]] = {}
        for h in self.state:
            hot_by_p.setdefault(annex.partition_of(h), []).append(h)
        victims = annex.pick_victims(
            {p: len(ks) for p, ks in hot_by_p.items()}, excess)
        for p in victims:
            items = [(h, _pack_key_state(self.state[h],
                                         self.key_values.get(h)))
                     for h in hot_by_p[p]]
            if not annex.spill(p, items):
                return  # degraded (SPILL_FALLBACK): stay resident, back off
            for h in hot_by_p[p]:
                self.state.pop(h, None)
                self.key_values.pop(h, None)

    def _identity(self, i: int):
        if self.acc_kinds[i] == "collect":
            return {}  # fresh multiplicity map per key
        from ..ops.aggregate import _identity

        return _identity(self.acc_kinds[i], self.acc_dtypes[i])

    def _key_columns(self, hashes) -> dict:
        """Group-by columns for the given key hashes (shared by emission and
        both checkpoint layouts)."""
        from ..batch import object_column

        cols: dict = {}
        for j, f in enumerate(self.key_fields):
            vals = [self.key_values.get(int(h), (None,) * len(self.key_fields))[j]
                    for h in hashes]
            sample = next((v for v in vals if v is not None), None)
            if isinstance(sample, (str, type(None))):
                cols[f] = object_column(vals)
            else:
                cols[f] = np.array(vals)
        return cols

    # ------------------------------------------------------- device lowering

    def _dev_dtypes(self) -> tuple:
        if self._synthetic_count:
            return self.acc_dtypes + (np.dtype(np.int64),)
        return self.acc_dtypes

    def _device(self):
        if self._dev is None:
            from ..config import config
            from ..ops.slot_agg import SlotAggregator

            dev = config().section("device")
            # every lane is a signed sum (count = sum of ±1)
            self._dev = SlotAggregator(
                tuple("sum" for _ in self._dev_dtypes()),
                self._dev_dtypes(),
                cap=dev.get("table-capacity", 65536),
                batch_cap=dev.get("batch-capacity", 8192),
                emit_cap=dev.get("emit-capacity", 8192),
                backend="jax",
                region_size=dev.get("region-size", 2048),
            )
        return self._dev

    def _process_device(self, hashes, ts, retracts, vals, batch) -> None:
        n = len(hashes)
        sign = np.where(retracts, -1, 1).astype(np.int64)
        signed = []
        for v, kind, dt in zip(vals, self.acc_kinds, self.acc_dtypes):
            if kind == "count":
                signed.append(sign.astype(dt))
            else:
                signed.append((np.asarray(v) * sign).astype(dt))
        if self._synthetic_count:
            signed.append(sign)
        self._device().update(hashes.view(np.uint64), np.zeros(n, dtype=np.int32),
                              signed)
        uniq, first = np.unique(hashes, return_index=True)
        mx = np.zeros(len(uniq), dtype=np.int64)
        np.maximum.at(mx, np.searchsorted(uniq, hashes), np.asarray(ts))
        lu = self._last_update
        for h, t in zip(uniq.tolist(), mx.tolist()):
            prev = lu.get(h)
            if prev is None or t > prev:
                lu[h] = t
        self.updated.update(uniq.tolist())
        if self.key_fields:
            cols = [np.asarray(batch[f]) for f in self.key_fields]
            kv = self.key_values
            for h, i in zip(uniq.tolist(), first.tolist()):
                if h not in kv:
                    kv[h] = tuple(c[i] for c in cols)

    def _device_values(self, keys: list[int]) -> list[tuple]:
        """Current accumulator tuples for the given key hashes (device
        gather + host spill lookups)."""
        agg = self._device()
        dts = self._dev_dtypes()
        key_u64 = np.array(keys, dtype=np.int64).view(np.uint64)
        slots = agg.slots_of(key_u64)
        on_dev = slots >= 0
        dev_vals = agg.read_slots(slots[on_dev]) if on_dev.any() else []
        out: list[list] = [[None] * len(dts) for _ in keys]
        di = 0
        for i, ondev in enumerate(on_dev.tolist()):
            if ondev:
                for j in range(len(dts)):
                    out[i][j] = dev_vals[j][di]
                di += 1
            else:
                spill = agg.spill.get((0, int(key_u64.view(np.int64)[i])))
                for j in range(len(dts)):
                    out[i][j] = spill[j] if spill is not None else dts[j].type(0)
        return [tuple(row) for row in out]

    def _flush_device(self, collector, evict_before) -> None:
        from ..ops.aggregate import finalize_aggs

        count_i = self._count_lane
        touched = sorted(self.updated)
        self.updated.clear()
        out_rows: list[tuple[int, tuple, bool]] = []
        dead: list[int] = []
        zero_keys: list[int] = []  # dead keys whose slots must reset exactly
        if touched:
            accs = self._device_values(touched)
            counts = np.array([int(a[count_i]) for a in accs], dtype=np.int64)
            if (counts < 0).any():
                raise RuntimeError(
                    "retract without matching append for key (updating "
                    "stream ordering violation)"
                )
            # columnar finalize across ALL touched keys at once — a per-key
            # Python finalize would re-introduce the loop this lowering
            # removes
            lanes = [np.array([a[j] for a in accs], dtype=d)
                     for j, d in enumerate(self.acc_dtypes)]
            finals = finalize_aggs([a[1] for a in self.aggregates], lanes)
            for i, h in enumerate(touched):
                emitted = self._emitted.get(h)
                if counts[i] == 0:
                    if emitted is not None:
                        out_rows.append((h, emitted, True))
                        self._emitted.pop(h, None)
                    dead.append(h)
                    zero_keys.append(h)
                    continue
                new_vals = tuple(f[i] for f in finals)
                if emitted is not None:
                    if emitted == new_vals:
                        continue
                    out_rows.append((h, emitted, True))
                out_rows.append((h, new_vals, False))
                self._emitted[h] = new_vals
        idle: list[int] = []
        if evict_before is not None:
            dead_set = set(dead)
            # sorted: see _flush — eviction retraction order must be
            # replay-stable, and dict order is not after a restore
            idle = sorted(h for h, t in self._last_update.items()
                          if t < evict_before and h not in dead_set)
            for h in idle:
                emitted = self._emitted.pop(h, None)
                if emitted is not None:
                    out_rows.append((h, emitted, True))
                dead.append(h)
        to_zero = zero_keys + idle
        if to_zero:
            # a returning key must restart from zero: scatter the negated
            # current values (pure sum lanes). This includes count==0 keys —
            # float lanes can hold rounding residue even when the integer
            # count lane reads exactly zero.
            vals = self._device_values(to_zero)
            neg = [np.array([-v[j] for v in vals], dtype=d)
                   for j, d in enumerate(self._dev_dtypes())]
            key_u64 = np.array(to_zero, dtype=np.int64).view(np.uint64)
            self._device().update(key_u64, np.zeros(len(to_zero), dtype=np.int32), neg)
        if out_rows:
            self._emit(out_rows, collector)
        for h in dead:
            self._last_update.pop(h, None)
            self.key_values.pop(h, None)
        self._dead_since_compact += len(dead)
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        """Dead keys leave their device slots assigned (eviction only zeroes
        values); once a quarter of the table has died, rebuild the store
        from the live snapshot so slot/spill capacity is reclaimed and
        checkpoints scale with LIVE keys, not keys-ever-seen."""
        dev = self._dev
        if dev is None or self._dead_since_compact < dev.cap // 4:
            return
        keys_u64, _bins, accs = dev.snapshot()
        live = accs[self._count_lane] > 0
        self._dev = None
        fresh = self._device()
        if live.any():
            fresh.restore(keys_u64[live], np.zeros(int(live.sum()), dtype=np.int32),
                          [a[live] for a in accs])
        self._dead_since_compact = 0

    # ------------------------------------------------------------------

    def _finalize(self, st: _KeyState) -> tuple:
        from ..ops.aggregate import finalize_aggs

        arrays = [np.array([a]) for a in st.accs]
        finals = finalize_aggs([a[1] for a in self.aggregates], arrays)
        return tuple(f[0] for f in finals)

    def _flush(self, collector, evict_before: Optional[int] = None) -> None:
        """Emit retract/append pairs for keys whose value changed
        (reference :638-700); TTL-evict idle keys with a retraction."""
        if self.device_mode:
            self._flush_device(collector, evict_before)
            return
        out_rows: list[tuple[int, tuple, bool]] = []  # (hash, values, is_retract)
        dead: list[int] = []
        if self._annex is not None:
            # a key can be spilled with its update pending (budget pressure
            # between flushes): promote it back so its emission reads the
            # exact accumulated state
            missing = sorted(h for h in self.updated if h not in self.state)
            if missing:
                for h, pk in sorted(self._annex.lookup_many(missing).items()):
                    st, kv = _unpack_key_state(pk)
                    self.state[h] = st
                    if kv is not None:
                        self.key_values[h] = kv
        for h in sorted(self.updated):
            st = self.state.get(h)
            if st is None:
                continue
            if st.count == 0:
                if st.emitted is not None:
                    out_rows.append((h, st.emitted, True))
                dead.append(h)
                continue
            new_vals = self._finalize(st)
            if st.emitted is not None:
                if st.emitted == new_vals:
                    continue  # suppress no-op updates (reference :649-652)
                out_rows.append((h, st.emitted, True))
            out_rows.append((h, new_vals, False))
            st.emitted = new_vals
        self.updated.clear()
        if evict_before is not None:
            if self._annex is not None:
                # cold keys expire too: promote every spilled key whose
                # newest copy is past the TTL so the eviction sweep below
                # retracts it exactly like a resident one (zone-map gated —
                # no file is read until the cutoff passes the oldest
                # surviving spilled row)
                for h, packed in self._annex.scan_expired(
                        evict_before, self.state.keys()):
                    st, kv = _unpack_key_state(packed)
                    self.state[h] = st
                    if kv is not None:
                        self.key_values[h] = kv
            dead_set = set(dead)
            # sorted: dict order diverges after a restore (rebuilt in
            # checkpoint-file order), so eviction retractions must not
            # leave in iteration order
            for h in sorted(h for h, st in self.state.items()
                            if st.last_update < evict_before
                            and h not in dead_set):
                st = self.state[h]
                if st.emitted is not None:
                    out_rows.append((h, st.emitted, True))
                dead.append(h)
        if out_rows:
            self._emit(out_rows, collector)
        # evict only after emission so retractions can still resolve key values
        for h in dead:
            self.state.pop(h, None)
            self.key_values.pop(h, None)

    def _emit(self, out_rows, collector) -> None:
        n = len(out_rows)
        cols: dict[str, np.ndarray] = {}
        if self.key_fields:
            cols.update(self._key_columns([h for h, _v, _r in out_rows]))
        for i, (name, _k, _e) in enumerate(self.aggregates):
            vals = [v[i] for _h, v, _r in out_rows]
            cols[name] = np.array(vals)
        cols[IS_RETRACT_FIELD] = np.array([r for _h, _v, r in out_rows], dtype=bool)
        cols[TIMESTAMP_FIELD] = np.full(n, self.max_event_time, dtype=np.int64)
        collector.collect(Batch(cols))

    # ------------------------------------------------------------------

    def handle_tick(self, ctx, collector):
        self._flush(collector, evict_before=self.max_event_time - self.ttl)

    def handle_watermark(self, watermark, ctx, collector):
        if not watermark.is_idle:
            self._flush(collector, evict_before=watermark.value - self.ttl)
        return watermark

    def on_close(self, ctx, collector):
        self._flush(collector)

    def handle_checkpoint(self, barrier, ctx, collector):
        # flush first so `emitted` mirrors what downstream has seen before the
        # barrier, then snapshot — otherwise un-flushed updates are lost on
        # restore because the `updated` set is not persisted
        self._flush(collector)
        # high-water mark persists UNCONDITIONALLY (an empty key snapshot
        # must not lose it — it stamps every emitted row's timestamp). The
        # RAW value, 0 included: a no-data subtask must restore its own 0,
        # not fall into the rescale merge and adopt a peer's higher mark
        persist_mark(ctx, "m", self.max_event_time)
        if self._annex is not None:
            from ..state.spill import checkpoint_manifest

            # one consistent tiered view per epoch: enforce the budget,
            # then snapshot — hot rows into "s" below, spilled runs BY
            # REFERENCE into the manifest (never re-uploaded)
            self._annex.epoch = barrier.epoch
            self._maybe_spill()
            checkpoint_manifest(ctx, "s__spill", self._annex)
        if self.device_mode:
            self._checkpoint_device(ctx)
            return
        tbl = ctx.table_manager.expiring_time_key("s", self.ttl)
        items = sorted(self.state.items())
        if not items:
            tbl.replace_all([])
            return
        n = len(items)
        n_agg = len(self.aggregates)
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: np.array([st.last_update for _h, st in items], dtype=np.int64),
            KEY_FIELD: np.array([h for h, _st in items], dtype=np.int64).view(np.uint64),
            "__count": np.array([st.count for _h, st in items], dtype=np.int64),
            "__has_emitted": np.array([st.emitted is not None for _h, st in items], dtype=bool),
        }
        import json as _json

        from ..batch import object_column

        for i, d in enumerate(self.acc_dtypes):
            if self.acc_kinds[i] == "collect":
                # multiplicity maps persist as JSON [value, count] pairs:
                # parquet has no stable encoding for dict-valued objects
                cols[f"__acc_{i}"] = object_column(
                    _json.dumps(sorted(st.accs[i].items(), key=str))
                    for _h, st in items)
            else:
                cols[f"__acc_{i}"] = np.array(
                    [st.accs[i] for _h, st in items], dtype=d)
        for i in range(n_agg):
            vals = [
                st.emitted[i] if st.emitted is not None else 0
                for _h, st in items
            ]
            cols[f"__emitted_{i}"] = np.array(vals)
        if self.key_fields:
            cols.update(self._key_columns([h for h, _st in items]))
        tbl.replace_all([Batch(cols)])


    # --------------------------------------------- device checkpoint/restore

    def _checkpoint_device(self, ctx) -> None:
        tbl = ctx.table_manager.expiring_time_key("s", self.ttl)
        if self._dev is None:
            tbl.replace_all([])
            return
        keys_u64, _bins, accs = self._dev.snapshot()
        signed = keys_u64.view(np.int64)
        live = accs[self._count_lane] > 0
        signed, accs = signed[live], [a[live] for a in accs]
        if len(signed) == 0:
            tbl.replace_all([])
            return
        n_agg = len(self.aggregates)
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: np.array(
                [self._last_update.get(int(h), self.max_event_time) for h in signed],
                dtype=np.int64),
            KEY_FIELD: signed.view(np.uint64),
            # explicit __count keeps the layout restorable by the HOST path
            # too (its sum-only configs have no count column to fall back on)
            "__count": accs[self._count_lane].astype(np.int64),
            "__has_emitted": np.array(
                [int(h) in self._emitted for h in signed], dtype=bool),
        }
        for i, (a, d) in enumerate(zip(accs, self._dev_dtypes())):
            cols[f"__acc_{i}"] = a.astype(d)
        for i in range(n_agg):
            cols[f"__emitted_{i}"] = np.array([
                self._emitted[int(h)][i] if int(h) in self._emitted else 0
                for h in signed
            ])
        if self.key_fields:
            cols.update(self._key_columns(signed))
        tbl.replace_all([Batch(cols)])

    def _restore_device(self, b: Batch) -> None:
        hashes = b.keys.astype(np.uint64)
        signed = hashes.view(np.int64)
        accs = []
        for i, d in enumerate(self._dev_dtypes()):
            col = f"__acc_{i}"
            if col in b:
                accs.append(np.asarray(b[col]).astype(d))
            elif i == self._count_lane and "__count" in b:
                # host-mode checkpoint layout: synthesize the count lane
                accs.append(np.asarray(b["__count"]).astype(d))
            else:
                accs.append(np.zeros(b.num_rows, dtype=d))
        self._device().restore(hashes, np.zeros(len(signed), dtype=np.int32), accs)
        emitted_mask = (np.asarray(b["__has_emitted"], dtype=bool)
                        if "__has_emitted" in b else np.zeros(len(signed), bool))
        n_agg = len(self.aggregates)
        key_cols = [b[f] for f in self.key_fields]
        for j in range(b.num_rows):
            h = int(signed[j])
            self._last_update[h] = int(b.timestamps[j])
            if emitted_mask[j]:
                self._emitted[h] = tuple(b[f"__emitted_{i}"][j] for i in range(n_agg))
            if self.key_fields:
                self.key_values[h] = tuple(c[j] for c in key_cols)


def merge_updating_rows(rows: list[dict]) -> list[dict]:
    """Materialize an updating stream: apply retract/append pairs in order and
    return the surviving rows (the reference smoke-test harness does the same
    to Debezium output before diffing, smoke_tests.rs:475-521)."""
    from collections import Counter

    live: Counter = Counter()
    for r in rows:
        retract = bool(r.get(IS_RETRACT_FIELD, r.get("_is_retract", False)))
        key = tuple(
            (k, v)
            for k, v in sorted(r.items())
            if k not in (IS_RETRACT_FIELD, TIMESTAMP_FIELD)
        )
        if retract:
            live[key] -= 1
        else:
            live[key] += 1
    out = []
    for key, cnt in live.items():
        for _ in range(cnt):
            out.append(dict(key))
    return out


@register_operator(OpName.UPDATING_AGGREGATE)
def _make_updating(cfg: dict):
    return UpdatingAggregate(cfg)
