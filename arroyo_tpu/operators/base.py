"""Operator trait boundary.

TPU-native equivalent of the reference's operator layer
(crates/arroyo-operator/src/operator.rs — ArrowOperator :1074, SourceOperator
:294, OperatorConstructor :55). Operators consume/produce columnar Batches;
window/join operator bodies dispatch into the jax runtime (arroyo_tpu.ops)
instead of DataFusion exec plans.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from ..batch import Batch, Schema
from ..types import (
    CheckpointBarrier,
    SourceFinishType,
    TaskInfo,
    Watermark,
)

if TYPE_CHECKING:
    from ..state.tables import TableManager
    from .collector import Collector


@dataclass
class TableSpec:
    """Declares a state table (reference operator.rs:1077 tables())."""

    name: str
    kind: str  # "global_keyed" | "expiring_time_key" | "key_time"
    retention_micros: int = 0
    schema: Optional[Schema] = None


class OperatorContext:
    """Per-subtask context handed to operator hooks
    (reference: arroyo-operator/src/context.rs OperatorContext)."""

    def __init__(
        self,
        task_info: TaskInfo,
        out_schema: Optional[Schema],
        table_manager: "TableManager",
        in_edge_of_input=None,
    ):
        self.task_info = task_info
        self.out_schema = out_schema
        self.table_manager = table_manager
        self.last_watermark: Optional[Watermark] = None
        # maps flat input index -> (edge_index, upstream_subtask)
        self._in_edge_of_input = in_edge_of_input or (lambda i: (0, i))

    def edge_of_input(self, input_index: int) -> int:
        return self._in_edge_of_input(input_index)[0]

    def watermark(self) -> Optional[int]:
        """Current event-time watermark in micros (None if idle/unset)."""
        if self.last_watermark is None:
            return None
        return self.last_watermark.value


def persist_mark(ctx: "OperatorContext", table: str, value) -> None:
    """Write this subtask's scalar meta mark (late-data barrier, event-time
    high-water, ...) into a global_keyed table — called UNCONDITIONALLY at
    every barrier, because a mark carried as a column on a state batch is
    silently dropped whenever the partial snapshot happens to be empty."""
    ctx.table_manager.global_keyed(table).insert(
        ctx.task_info.subtask_index, value)


def restore_marks(ctx: "OperatorContext", table: str) -> list:
    """Every prior subtask's non-None mark from a meta table. The merge is
    the caller's: ``max`` for watermark-aligned boundaries (aligned barriers
    mean all subtasks saw the same watermark, so max is rescale-safe);
    data-derived per-subtask marks should prefer their OWN entry
    (``global_keyed(table).get(subtask_index)``) and fall back to a merge
    only on rescale."""
    return [v for _k, v in ctx.table_manager.global_keyed(table).items()
            if v is not None]


class Operator:
    """Mid-pipeline operator (reference ArrowOperator, operator.rs:1074-1183).

    Hooks are called from the task run loop (engine/task.py) which owns
    barrier alignment, watermark merging, and end-of-data accounting.
    """

    def name(self) -> str:
        return type(self).__name__

    def tables(self) -> list[TableSpec]:
        return []

    def on_start(self, ctx: OperatorContext) -> None:
        pass

    def process_batch(
        self, batch: Batch, ctx: OperatorContext, collector: "Collector", input_index: int = 0
    ) -> None:
        raise NotImplementedError

    def handle_watermark(
        self, watermark: Watermark, ctx: OperatorContext, collector: "Collector"
    ) -> Optional[Watermark]:
        """Return the watermark to forward downstream, or None to hold it
        (reference operator.rs:1138)."""
        return watermark

    def handle_checkpoint(
        self, barrier: CheckpointBarrier, ctx: OperatorContext, collector: "Collector"
    ) -> None:
        """Flush in-flight device/host state into state tables before the
        table manager snapshots them (reference operator.rs handle_checkpoint)."""

    def handle_commit(self, epoch: int, ctx: OperatorContext) -> None:
        pass

    def is_committing(self) -> bool:
        return False

    def tick_interval_micros(self) -> Optional[int]:
        """If set, handle_tick is invoked at roughly this period
        (reference operator.rs:1167 handle_tick)."""
        return None

    def handle_tick(self, ctx: OperatorContext, collector: "Collector") -> None:
        pass

    def on_close(self, ctx: OperatorContext, collector: "Collector") -> None:
        """All inputs reached end-of-data; emit any remaining state."""


class SourceOperator:
    """Source (reference SourceOperator, operator.rs:294-342).

    ``run`` drives the source; it must call ``ctx_poll`` helpers frequently:
    the run loop passes a SourceContext whose ``poll_control`` surfaces
    checkpoint/stop commands from the engine.
    """

    def name(self) -> str:
        return type(self).__name__

    def tables(self) -> list[TableSpec]:
        return []

    def on_start(self, ctx: OperatorContext) -> None:
        pass

    def is_committing(self) -> bool:
        """True if this source defers side effects (e.g. broker acks) to the
        engine's post-checkpoint commit message; the engine then delivers
        ``ControlMessage(kind="commit", epoch=...)`` via poll_control once
        the epoch's job-level metadata is durable."""
        return False

    def run(self, ctx: OperatorContext, collector: "Collector") -> SourceFinishType:
        raise NotImplementedError

    def on_close(self, ctx: OperatorContext, collector: "Collector") -> None:
        pass
