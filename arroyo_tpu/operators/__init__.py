from .base import Operator, OperatorContext, SourceOperator, TableSpec  # noqa: F401
from .collector import Collector, OutEdge  # noqa: F401
