"""Collector: output routing + keyed repartition + micro-batch coalescing.

Equivalent of the reference's ArrowCollector
(crates/arroyo-operator/src/context.rs:502-603): hash routing keys ->
server_for_hash -> sort -> slice per destination; round-robin slices with a
rotating offset when unkeyed; signals broadcast to every output partition.

Coalescing (ISSUE 5): sub-threshold output batches accumulate here instead
of paying full per-batch overhead through queue -> (data plane) -> inbox per
tiny emit. Pending rows flush when ``engine.coalesce.max-rows``/``max-bytes``
trips, when the oldest pending row exceeds ``max-delay-ms`` (the task run
loop polls ``flush_expired``), or — ALWAYS, and first — when any signal is
broadcast, so watermarks, barriers, stop, and end-of-data can never reorder
past buffered rows and checkpoint recovery stays byte-exact.

On a TPU mesh this repartition disappears into device collectives
(arroyo_tpu.parallel lowers keyed exchange to all_to_all over ICI); this host
collector remains the cross-process / cross-operator path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from ..batch import KEY_FIELD, Batch
from ..graph import EdgeType
from ..hashing import servers_for_hashes
from ..types import Signal

if TYPE_CHECKING:
    from ..engine.queues import TaskInbox


@dataclass
class OutEdge:
    """One logical out-edge: destinations are the downstream subtask inboxes,
    with this producer's flat input index at each destination."""

    edge_type: EdgeType
    dests: Sequence[TaskInbox]
    dest_input_index: Sequence[int]  # parallel to dests: our input idx there


class Collector:
    def __init__(self, out_edges: list[OutEdge], subtask_index: int):
        from ..config import config

        self.out_edges = out_edges
        self.subtask_index = subtask_index
        # decorrelate round-robin starts across producers without
        # randomness (LR103): replays must route identically, or restored
        # runs diverge from the run that wrote the checkpoint
        self._rr_offset = (subtask_index * 0x9E3779B1) & 0xFFFF
        self.batches_sent = 0
        self.rows_sent = 0
        self.metrics = None  # TaskMetrics, attached by the owning Task
        c = config()
        self.coalesce = bool(c.get("engine.coalesce.enabled", True))
        self.co_max_rows = int(c.get("engine.coalesce.max-rows", 4096))
        self.co_max_bytes = int(c.get("engine.coalesce.max-bytes", 1 << 20))
        self.co_max_delay_s = float(c.get("engine.coalesce.max-delay-ms", 5)) / 1e3
        self._pending: list[Batch] = []
        self._pending_rows = 0
        self._pending_bytes = 0
        self._pending_since = 0.0
        self._pending_cols: frozenset = frozenset()

    def collect(self, batch: Batch) -> None:
        if batch.num_rows == 0:
            return
        if not self.coalesce:
            self._route(batch)
            return
        if self._pending and self._pending_cols != frozenset(batch.columns):
            # schema change between emits (e.g. an outer join's matched vs
            # padded shapes): never concat across it
            self.flush()
        if not self._pending and batch.num_rows >= self.co_max_rows:
            self._route(batch)  # already full-size: skip the copy
            return
        if not self._pending:
            self._pending_since = time.monotonic()  # lint: waive LR109 — coalescing max-delay deadline clock, not self-measurement
            self._pending_cols = frozenset(batch.columns)
        self._pending.append(batch)
        self._pending_rows += batch.num_rows
        self._pending_bytes += batch.nbytes()
        if (self._pending_rows >= self.co_max_rows
                or self._pending_bytes >= self.co_max_bytes):
            self.flush()

    def flush(self) -> None:
        """Route everything pending as one coalesced batch."""
        if not self._pending:
            return
        batches, self._pending = self._pending, []
        self._pending_rows = self._pending_bytes = 0
        self._route(Batch.concat(batches))

    def flush_expired(self, now: float | None = None) -> None:
        """Time-based flush: called from the task run loop between items so
        a lull in traffic cannot hold sub-threshold rows forever."""
        # lint: waive LR109 — coalescing max-delay deadline clock, not self-measurement
        if self._pending and (now or time.monotonic()) - self._pending_since \
                >= self.co_max_delay_s:
            self.flush()

    def flush_deadline(self) -> Optional[float]:
        """Monotonic time by which pending rows must flush (None when
        nothing is pending). The run loop bounds its queue wait with this so
        the max-delay-ms contract holds without reaching into internals."""
        if not self._pending:
            return None
        return self._pending_since + self.co_max_delay_s

    def _route(self, batch: Batch) -> None:
        self.batches_sent += 1
        self.rows_sent += batch.num_rows
        if self.metrics is not None:
            self.metrics.add("arroyo_worker_batches_sent")
            self.metrics.add("arroyo_worker_messages_sent", batch.num_rows)
            self.metrics.add("arroyo_worker_bytes_sent", batch.nbytes())
            self.metrics.emit_batch_rows.observe(batch.num_rows)
        for edge in self.out_edges:
            n = len(edge.dests)
            if n == 1:
                edge.dests[0].put(edge.dest_input_index[0], batch)
            elif edge.edge_type == EdgeType.FORWARD:
                d = self.subtask_index % n
                edge.dests[d].put(edge.dest_input_index[d], batch)
            elif KEY_FIELD in batch:
                self._shuffle_keyed(batch, edge)
            else:
                self._shuffle_round_robin(batch, edge)

    def _shuffle_keyed(self, batch: Batch, edge: OutEdge) -> None:
        n = len(edge.dests)
        if self.metrics is not None and self.metrics.sketch is not None:
            # key-skew sketch, producer side: the shuffle boundary is where
            # a hot key melts one downstream subtask (obs/sketch.py); at the
            # default sample-every=1 this is row-deterministic under replay
            # no matter how coalescing re-draws batch boundaries
            self.metrics.sketch.observe(batch.keys)
        from .. import native

        part = native.partition(batch.keys, n)
        if part is not None:
            # native counting-sort permutation (cpp/arroyo_host.cc
            # ah_partition — the reference's repartition hot path)
            order, bounds = part
        else:
            dests = servers_for_hashes(batch.keys, n)
            order = np.argsort(dests, kind="stable")
            sorted_dests = dests[order]
            bounds = np.searchsorted(sorted_dests, np.arange(n + 1))
        sorted_batch = batch.take(order)
        for d in range(n):
            lo, hi = bounds[d], bounds[d + 1]
            if hi > lo:
                edge.dests[d].put(edge.dest_input_index[d], sorted_batch.slice(lo, hi))

    def _shuffle_round_robin(self, batch: Batch, edge: OutEdge) -> None:
        # Rotating even slices (reference context.rs:539-554).
        n = len(edge.dests)
        rows = batch.num_rows
        per = (rows + n - 1) // n
        start_dest = self._rr_offset % n
        self._rr_offset += 1
        for i in range(n):
            lo, hi = i * per, min((i + 1) * per, rows)
            if hi > lo:
                d = (start_dest + i) % n
                edge.dests[d].put(edge.dest_input_index[d], batch.slice(lo, hi))

    def broadcast(self, signal: Signal) -> None:
        """Signals go to every output partition (reference context.rs:655-669).
        Pending coalesced rows flush FIRST: a signal must never overtake the
        data emitted before it."""
        self.flush()
        for edge in self.out_edges:
            for dest, idx in zip(edge.dests, edge.dest_input_index):
                dest.put(idx, signal)
