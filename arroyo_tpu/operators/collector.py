"""Collector: output routing + keyed repartition.

Equivalent of the reference's ArrowCollector
(crates/arroyo-operator/src/context.rs:502-603): hash routing keys ->
server_for_hash -> sort -> slice per destination; round-robin slices with a
rotating offset when unkeyed; signals broadcast to every output partition.

On a TPU mesh this repartition disappears into device collectives
(arroyo_tpu.parallel lowers keyed exchange to all_to_all over ICI); this host
collector remains the cross-process / cross-operator path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Sequence

import numpy as np

from ..batch import KEY_FIELD, Batch
from ..graph import EdgeType
from ..hashing import servers_for_hashes
from ..types import Signal

if TYPE_CHECKING:
    from ..engine.queues import TaskInbox


@dataclass
class OutEdge:
    """One logical out-edge: destinations are the downstream subtask inboxes,
    with this producer's flat input index at each destination."""

    edge_type: EdgeType
    dests: Sequence[TaskInbox]
    dest_input_index: Sequence[int]  # parallel to dests: our input idx there


class Collector:
    def __init__(self, out_edges: list[OutEdge], subtask_index: int):
        self.out_edges = out_edges
        self.subtask_index = subtask_index
        # decorrelate round-robin starts across producers without
        # randomness (LR103): replays must route identically, or restored
        # runs diverge from the run that wrote the checkpoint
        self._rr_offset = (subtask_index * 0x9E3779B1) & 0xFFFF
        self.batches_sent = 0
        self.rows_sent = 0
        self.metrics = None  # TaskMetrics, attached by the owning Task

    def collect(self, batch: Batch) -> None:
        if batch.num_rows == 0:
            return
        self.batches_sent += 1
        self.rows_sent += batch.num_rows
        if self.metrics is not None:
            self.metrics.add("arroyo_worker_batches_sent")
            self.metrics.add("arroyo_worker_messages_sent", batch.num_rows)
            self.metrics.add("arroyo_worker_bytes_sent", batch.nbytes())
        for edge in self.out_edges:
            n = len(edge.dests)
            if n == 1:
                edge.dests[0].put(edge.dest_input_index[0], batch)
            elif edge.edge_type == EdgeType.FORWARD:
                d = self.subtask_index % n
                edge.dests[d].put(edge.dest_input_index[d], batch)
            elif KEY_FIELD in batch:
                self._shuffle_keyed(batch, edge)
            else:
                self._shuffle_round_robin(batch, edge)

    def _shuffle_keyed(self, batch: Batch, edge: OutEdge) -> None:
        n = len(edge.dests)
        from .. import native

        part = native.partition(batch.keys, n)
        if part is not None:
            # native counting-sort permutation (cpp/arroyo_host.cc
            # ah_partition — the reference's repartition hot path)
            order, bounds = part
        else:
            dests = servers_for_hashes(batch.keys, n)
            order = np.argsort(dests, kind="stable")
            sorted_dests = dests[order]
            bounds = np.searchsorted(sorted_dests, np.arange(n + 1))
        sorted_batch = batch.take(order)
        for d in range(n):
            lo, hi = bounds[d], bounds[d + 1]
            if hi > lo:
                edge.dests[d].put(edge.dest_input_index[d], sorted_batch.slice(lo, hi))

    def _shuffle_round_robin(self, batch: Batch, edge: OutEdge) -> None:
        # Rotating even slices (reference context.rs:539-554).
        n = len(edge.dests)
        rows = batch.num_rows
        per = (rows + n - 1) // n
        start_dest = self._rr_offset % n
        self._rr_offset += 1
        for i in range(n):
            lo, hi = i * per, min((i + 1) * per, rows)
            if hi > lo:
                d = (start_dest + i) % n
                edge.dests[d].put(edge.dest_input_index[d], batch.slice(lo, hi))

    def broadcast(self, signal: Signal) -> None:
        """Signals go to every output partition (reference context.rs:655-669)."""
        for edge in self.out_edges:
            for dest, idx in zip(edge.dests, edge.dest_input_index):
                dest.put(idx, signal)
