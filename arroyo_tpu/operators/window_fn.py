"""SQL window-function (OVER clause) operator.

Reference behavior: crates/arroyo-worker/src/arrow/window_fn.rs:34 — rows
buffer per event-time bucket (upstream windowed operators stamp the window
start); when the watermark passes a bucket, rows are partitioned and sorted
and the window-function plan runs, emitting the input columns plus the
computed function columns.

Supported functions: row_number, rank, dense_rank, plus unbounded-partition
aggregates (sum/count/min/max/avg). Everything is vectorized: one lexsort per
bucket, segment boundaries via flatnonzero, per-partition reductions via
reduceat broadcast back with repeat.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..engine.engine import register_operator
from ..expr import Expr, eval_expr
from ..graph import OpName
from ..hashing import hash_columns
from ..operators.base import Operator, TableSpec, persist_mark, restore_marks


def _sortable(col: np.ndarray, desc: bool) -> np.ndarray:
    """Map a column to an ascending-sortable numeric key. Descending order
    negates a rank transform for everything but floats — negating raw
    unsigned columns wraps (0 would sort first) and int64 min overflows."""
    if col.dtype == object:
        import pandas as pd

        codes, uniques = pd.factorize(col, use_na_sentinel=True)
        order = np.argsort(np.asarray(uniques, dtype=object), kind="stable")
        rank_of = np.empty(len(uniques) + 1, dtype=np.int64)
        rank_of[order] = np.arange(len(uniques))
        rank_of[-1] = -1  # None sorts first
        key = rank_of[codes]
    elif col.dtype == np.bool_:
        key = col.astype(np.int64)
    elif col.dtype.kind in "iu":
        _u, key = np.unique(col, return_inverse=True)
        key = key.astype(np.int64)
    else:
        key = col
    return -key if desc else key


class WindowFunctionOperator(Operator):
    """config: partition_fields: [str], order_by: [(Expr, asc_bool)],
    functions: [(out_name, kind, Expr|None)], retain_fields: [str]|None
    (input columns to carry through; default all)."""

    def __init__(self, cfg: dict):
        self.partition_fields: list[str] = list(cfg.get("partition_fields", ()))
        self.order_by: list[tuple[Expr, bool]] = list(cfg.get("order_by", ()))
        self.functions: list[tuple[str, str, Optional[Expr]]] = list(cfg["functions"])
        self.retain_fields = cfg.get("retain_fields")
        self.buf: dict[int, list[Batch]] = {}
        self.emitted_before: Optional[int] = None
        self.late_rows = 0  # state: ephemeral — observability counter (obs/profile.py export); never read into emitted data

    def tables(self):
        return [
            TableSpec("input", "expiring_time_key"),
            TableSpec("e", "global_keyed"),  # late-data barrier
        ]

    def on_start(self, ctx):
        tbl = ctx.table_manager.expiring_time_key("input")
        for b in tbl.all_batches():
            self._buffer(b)
        tbl.replace_all([])
        barriers = restore_marks(ctx, "e")
        if barriers:
            self.emitted_before = max(barriers)

    def _buffer(self, batch: Batch) -> None:
        ts = batch.timestamps
        uniq = np.unique(ts)
        for t in uniq.tolist():
            if len(uniq) == 1:
                self.buf.setdefault(int(t), []).append(batch)
            else:
                self.buf.setdefault(int(t), []).append(batch.filter(ts == t))

    def process_batch(self, batch, ctx, collector, input_index=0):
        if self.emitted_before is not None:
            late = batch.timestamps < self.emitted_before
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
        self._buffer(batch)

    def handle_watermark(self, watermark, ctx, collector):
        if not watermark.is_idle:
            self._emit_closed(watermark.value, collector)
        return watermark

    def on_close(self, ctx, collector):
        self._emit_closed(None, collector)

    def _emit_closed(self, before: Optional[int], collector) -> None:
        for t in sorted(k for k in self.buf if before is None or k < before):
            batches = self.buf.pop(t)
            self._compute_and_emit(Batch.concat(batches), collector)
        if before is not None and (
            self.emitted_before is None or before > self.emitted_before
        ):
            self.emitted_before = before

    def _compute_and_emit(self, b: Batch, collector) -> None:
        n = b.num_rows
        if n == 0:
            return
        # sort: partition hash first, then order-by keys
        sort_keys: list[np.ndarray] = []
        for e, asc in reversed(self.order_by):
            col = np.asarray(eval_expr(e, b.columns, n))
            sort_keys.append(_sortable(col, not asc))
        if self.partition_fields:
            part = hash_columns([np.asarray(b[f]) for f in self.partition_fields])
            part_signed = part.view(np.int64)
        else:
            part_signed = np.zeros(n, dtype=np.int64)
        sort_keys.append(part_signed)
        order = np.lexsort(tuple(sort_keys))
        sb = b.take(order)
        p_s = part_signed[order]
        brk = np.ones(n, dtype=bool)
        brk[1:] = p_s[1:] != p_s[:-1]
        starts = np.flatnonzero(brk)
        counts = np.diff(np.append(starts, n))
        part_start = np.repeat(starts, counts)  # per-row partition start idx
        pos = np.arange(n)
        # order-key change points (for rank/dense_rank ties) — reuse the
        # already-built sort keys, permuted into sorted order
        if self.order_by:
            obrk = brk.copy()
            for k in sort_keys[:-1]:  # all but the partition key
                k_sorted = k[order]
                obrk[1:] |= k_sorted[1:] != k_sorted[:-1]
        else:
            obrk = brk
        cols = dict(sb.columns)
        if self.retain_fields is not None:
            keep = set(self.retain_fields) | {TIMESTAMP_FIELD}
            if KEY_FIELD in cols:
                keep.add(KEY_FIELD)
            cols = {k: v for k, v in cols.items() if k in keep}
        for out_name, kind, e in self.functions:
            if kind == "row_number":
                cols[out_name] = pos - part_start + 1
            elif kind == "rank":
                # index of the first row of the tie-group, relative to partition
                tie_start = pos[obrk]
                cols[out_name] = np.repeat(tie_start, np.diff(np.append(np.flatnonzero(obrk), n))) - part_start + 1
            elif kind == "dense_rank":
                new_in_part = np.cumsum(obrk) - 1
                first_of_part = (np.cumsum(obrk) - 1)[part_start]
                cols[out_name] = new_in_part - first_of_part + 1
            elif kind in ("sum", "count", "min", "max", "avg"):
                if kind == "count" or e is None:
                    vals = np.ones(n, dtype=np.int64)
                else:
                    vals = np.asarray(eval_expr(e, sb.columns, n))
                if kind in ("sum", "count"):
                    red = np.add.reduceat(vals, starts)
                elif kind == "min":
                    red = np.minimum.reduceat(vals, starts)
                elif kind == "max":
                    red = np.maximum.reduceat(vals, starts)
                else:
                    s = np.add.reduceat(vals.astype(np.float64), starts)
                    red = s / counts
                cols[out_name] = np.repeat(red, counts)
            else:
                raise NotImplementedError(f"window function {kind}")
        collector.collect(Batch(cols))

    def handle_checkpoint(self, barrier, ctx, collector):
        tbl = ctx.table_manager.expiring_time_key("input")
        tbl.replace_all([b for lst in self.buf.values() for b in lst])
        persist_mark(ctx, "e", self.emitted_before)


@register_operator(OpName.WINDOW_FUNCTION)
def _make_window_fn(cfg: dict):
    return WindowFunctionOperator(cfg)
