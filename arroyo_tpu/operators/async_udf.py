"""Async UDF operator: bounded-concurrency out-of-band compute.

Reference: crates/arroyo-worker/src/arrow/async_udf.rs:31 — ordered or
unordered in-flight async UDF calls with a max concurrency, watermark-held
emission, and the in-flight set captured at checkpoints. Here calls run on a
thread pool (the Python analog of the reference's tokio tasks); barriers and
watermarks drain the in-flight set first, which subsumes persisting it — the
snapshot is taken with nothing in flight, exactly one row per input emitted.
"""

from __future__ import annotations

from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch, Field
from ..engine.engine import register_operator
from ..expr import Expr, eval_expr
from ..graph import OpName
from ..operators.base import Operator
from ..types import Watermark


class AsyncUdfOperator(Operator):
    """config: name, fn (callable), arg_exprs: [Expr], out_name,
    return_dtype, ordered: bool, max_concurrency, timeout_s,
    retain_fields: [str] | None (input columns carried through)."""

    def __init__(self, cfg: dict):
        self.name_ = str(cfg.get("name", "async_udf"))
        self.fn = cfg["fn"]
        self.arg_exprs: list[Expr] = list(cfg["arg_exprs"])
        self.out_name = str(cfg.get("out_name", self.name_))
        self.return_dtype = str(cfg.get("return_dtype", "float64"))
        self.ordered = bool(cfg.get("ordered", True))
        self.max_concurrency = int(cfg.get("max_concurrency", 64))
        self.timeout_s = float(cfg.get("timeout_s", 30.0))
        self.retain_fields = cfg.get("retain_fields")
        self._pool: Optional[ThreadPoolExecutor] = None
        # (seq, carried_row_cols, future); seq preserves input order
        self._in_flight: list[tuple[int, dict, Future]] = []
        self._seq = 0  # state: ephemeral — orders in-flight calls within one incarnation; the in-flight set drains at every barrier

    def name(self) -> str:
        return f"async:{self.name_}"

    def on_start(self, ctx):
        self._pool = ThreadPoolExecutor(
            max_workers=min(self.max_concurrency, 64),
            thread_name_prefix=f"audf-{self.name_}",
        )

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        args_cols = [np.asarray(eval_expr(e, batch.columns, n)) for e in self.arg_exprs]
        keep = self.retain_fields
        if keep is None:
            keep = [c for c in batch.columns if c != KEY_FIELD]
        for i in range(n):
            while len(self._in_flight) >= self.max_concurrency:
                self._emit_some(collector, block=True)
            carried = {c: batch.columns[c][i] for c in keep}
            args = tuple(a[i] for a in args_cols)
            fut = self._pool.submit(self.fn, *args)
            self._in_flight.append((self._seq, carried, fut))
            self._seq += 1
        self._emit_some(collector, block=False)

    # ------------------------------------------------------------------

    def _emit_some(self, collector, block: bool) -> None:
        if not self._in_flight:
            return
        if self.ordered:
            ready: list[tuple[int, dict, Future]] = []
            while self._in_flight and (
                self._in_flight[0][2].done() or (block and not ready)
            ):
                seq, carried, fut = self._in_flight[0]
                fut.result(timeout=self.timeout_s if block else None)
                ready.append(self._in_flight.pop(0))
                block = False  # only force the head
            self._emit_rows(ready, collector)
        else:
            if block:
                wait([f for _s, _c, f in self._in_flight],
                     timeout=self.timeout_s, return_when=FIRST_COMPLETED)
            done = [t for t in self._in_flight if t[2].done()]
            if not done and block:
                # nothing completed within timeout_s: fail like the ordered
                # path does, instead of letting callers spin forever
                raise TimeoutError(
                    f"async UDF {self.name_}: no call completed within "
                    f"{self.timeout_s}s ({len(self._in_flight)} in flight)"
                )
            if done:
                self._in_flight = [t for t in self._in_flight if not t[2].done()]
                self._emit_rows(done, collector)

    def _drain(self, collector) -> None:
        while self._in_flight:
            self._emit_some(collector, block=True)

    def _emit_rows(self, items: list, collector) -> None:
        if not items:
            return
        cols: dict[str, list] = {}
        for _seq, carried, fut in items:
            result = fut.result(timeout=self.timeout_s)
            # lint: waive LR204 — carried is a per-row dict built in process_batch's column order; identical construction on replay
            for k, v in carried.items():
                cols.setdefault(k, []).append(v)
            cols.setdefault(self.out_name, []).append(result)
        out: dict[str, np.ndarray] = {}
        for k, vals in cols.items():
            if k == self.out_name:
                dt = Field("_", self.return_dtype).numpy_dtype()
                out[k] = np.array(vals, dtype=dt)
            else:
                sample = vals[0]
                if isinstance(sample, (str, bytes, type(None))):
                    out[k] = np.array(vals, dtype=object)
                else:
                    out[k] = np.array(vals)
        if TIMESTAMP_FIELD not in out:
            out[TIMESTAMP_FIELD] = np.zeros(len(items), dtype=np.int64)
        collector.collect(Batch(out))

    # ------------------------------------------------------------------

    def handle_watermark(self, watermark: Watermark, ctx, collector):
        # results for rows behind the watermark must be emitted before it
        self._drain(collector)
        return watermark

    def handle_checkpoint(self, barrier, ctx, collector):
        # snapshot with an empty in-flight set: every accepted row's result
        # is downstream of (and thus covered by) this barrier
        self._drain(collector)

    def on_close(self, ctx, collector):
        self._drain(collector)
        if self._pool is not None:
            self._pool.shutdown(wait=False)


@register_operator(OpName.ASYNC_UDF)
def _make_async_udf(cfg: dict):
    return AsyncUdfOperator(cfg)
