"""Join operators.

- InstantJoin: windowed stream-stream join (reference:
  crates/arroyo-worker/src/arrow/instant_join.rs:38). Upstream window
  aggregates stamp each row with its window start, so both inputs arrive
  bucketed by exact timestamp; rows buffer per timestamp and the join for
  bucket t executes when the merged watermark passes t. Vectorized hash join
  on the routing-key column (both sides are keyed on the equi-join columns,
  so equal keys share a hash; hashes are 64-bit and collision-checked by the
  planner's key columns being carried through).
- JoinWithExpiration: updating non-windowed join (reference:
  join_with_expiration.rs:29) — symmetric hash join over TTL'd key-time
  buffers, emitting retract/append pairs so outer joins stay consistent as
  matches appear and disappear.
- LookupJoin: stream enriched against an external keyed table through a
  lookup connector with a TTL'd cache (reference: lookup_join.rs:35).
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec
from ..types import Signal
from .updating_aggregate import IS_RETRACT_FIELD


def _object_col(values: list) -> np.ndarray:
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def _hash_join_indices(
    left_keys: np.ndarray, right_keys: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Inner-join row index pairs (li, ri) where keys match, vectorized:
    sort the right side once, binary-search each left key, expand ranges."""
    order = np.argsort(right_keys, kind="stable")
    rk = right_keys[order]
    lo = np.searchsorted(rk, left_keys, side="left")
    hi = np.searchsorted(rk, left_keys, side="right")
    counts = hi - lo
    li = np.repeat(np.arange(len(left_keys)), counts)
    # for each left row, offsets lo[l]..hi[l] into the sorted right
    if len(li):
        within = np.arange(len(li)) - np.repeat(
            np.cumsum(counts) - counts, counts
        )
        ri = order[np.repeat(lo, counts) + within]
    else:
        ri = np.empty(0, dtype=np.int64)
    return li, ri


class InstantJoin(Operator):
    """config: join_type: inner|left|right|full, left_names/right_names:
    [(out_name, src_name)] column selections per side, backend override
    "jax"|"numpy"|None (default: device when enabled).

    Device lowering: the sort/search phase of each window's join runs on
    the device (ops/join_probe.py) and its result streams back while later
    batches keep flowing — closes queue in order and each watermark is
    forwarded only after its windows' rows, the same pipelining discipline
    as the window aggregates."""

    def __init__(self, cfg: dict):
        from ..config import config

        self.join_type: str = cfg.get("join_type", "inner")
        self.left_names: list[tuple[str, str]] = list(cfg["left_names"])
        self.right_names: list[tuple[str, str]] = list(cfg["right_names"])
        self.backend = cfg.get("backend") or (
            "jax" if config().get("device.enabled") else "numpy"
        )
        # below this many rows on either side, the numpy join is cheaper
        # than a device dispatch
        self.device_min_rows = int(config().get("device.join-min-rows", 2048))
        # t -> [left batches], [right batches]
        self.buf: dict[int, tuple[list, list]] = {}
        self.late_rows = 0
        self.emitted_before: Optional[int] = None
        # in-flight closes: (JoinHandle|None, t, lb, rb, Watermark|None)
        self._pending: deque = deque()

    def tables(self):
        return [
            TableSpec("left", "expiring_time_key"),
            TableSpec("right", "expiring_time_key"),
            TableSpec("e", "global_keyed"),  # late-data barrier
        ]

    def on_start(self, ctx):
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name)
            for b in tbl.all_batches():
                self._buffer(b, side)
            tbl.replace_all([])
        barriers = [
            v for _k, v in ctx.table_manager.global_keyed("e").items() if v is not None
        ]
        if barriers:
            self.emitted_before = max(barriers)

    def _buffer(self, batch: Batch, side: int) -> None:
        ts = batch.timestamps
        uniq = np.unique(ts)
        for t in uniq.tolist():
            ent = self.buf.setdefault(int(t), ([], []))
            if len(uniq) == 1:
                ent[side].append(batch)
            else:
                ent[side].append(batch.filter(ts == t))

    def process_batch(self, batch, ctx, collector, input_index=0):
        if self._pending:
            self._drain_pending(collector)
        side = ctx.edge_of_input(input_index)
        if self.emitted_before is not None:
            late = batch.timestamps < self.emitted_before
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
        self._buffer(batch, side)

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            self._drain_pending(collector, force=True)
            return watermark
        scheduled = self._schedule_closed(watermark.value, watermark, collector)
        self._drain_pending(collector)
        if scheduled or self._pending:
            return None  # watermark rides the pending queue, in order
        return watermark

    def on_close(self, ctx, collector):
        self._schedule_closed(None, None, collector)
        self._drain_pending(collector, force=True)

    def _schedule_closed(self, before: Optional[int], wm, collector) -> bool:
        """Queue the join for every window closed by the watermark; the
        watermark marker is appended after its windows so emission order is
        preserved. Returns True when anything was queued."""
        ts_list = sorted(t for t in self.buf if before is None or t < before)
        for t in ts_list:
            left, right = self.buf.pop(t)
            while len(self._pending) >= 16:  # bound in-flight joins
                handle, pt, lb, rb, pwm = self._pending.popleft()
                if pwm is not None:
                    collector.broadcast(Signal.watermark_of(pwm))
                else:
                    self._join_and_emit(pt, lb, rb, handle, collector)
            self._pending.append(self._start_join(t, left, right))
        if before is not None and (
            self.emitted_before is None or before > self.emitted_before
        ):
            self.emitted_before = before
        if wm is not None:
            if self._pending or ts_list:
                self._pending.append((None, None, None, None, wm))
                return True
            return False
        return bool(ts_list)

    def _start_join(self, t: int, left: list, right: list):
        lb = Batch.concat(left) if left else None
        rb = Batch.concat(right) if right else None
        handle = None
        if lb is not None and rb is not None:
            n = max(lb.num_rows, rb.num_rows)
            if self.backend == "jax" and n >= self.device_min_rows:
                from ..ops.join_probe import device_join_start

                lk = lb.keys.astype(np.uint64).view(np.int64)
                rk = rb.keys.astype(np.uint64).view(np.int64)
                handle = device_join_start(lk, rk)
        return (handle, t, lb, rb, None)

    def _drain_pending(self, collector, force: bool = False) -> None:
        while self._pending:
            handle, t, lb, rb, wm = self._pending[0]
            if wm is None and handle is not None and not force and not handle.is_ready():
                return
            self._pending.popleft()
            if wm is not None:
                collector.broadcast(Signal.watermark_of(wm))
                continue
            self._join_and_emit(t, lb, rb, handle, collector)

    def _join_and_emit(self, t: int, lb, rb, handle, collector) -> None:
        jt = self.join_type
        if lb is None and rb is None:
            return
        if lb is None:
            if jt in ("right", "full"):
                self._emit(t, None, rb, None, None, collector)
            return
        if rb is None:
            if jt in ("left", "full"):
                self._emit(t, lb, None, None, None, collector)
            return
        if handle is not None:
            li, ri = handle.result()
        else:
            lk = lb.keys.astype(np.uint64).view(np.int64)
            rk = rb.keys.astype(np.uint64).view(np.int64)
            li, ri = _hash_join_indices(lk, rk)
        if len(li):
            self._emit(t, lb, rb, li, ri, collector)
        if jt in ("left", "full"):
            unmatched = np.ones(lb.num_rows, dtype=bool)
            unmatched[li] = False
            if unmatched.any():
                self._emit(t, lb.filter(unmatched), None, None, None, collector)
        if jt in ("right", "full"):
            unmatched = np.ones(rb.num_rows, dtype=bool)
            unmatched[ri] = False
            if unmatched.any():
                self._emit(t, None, rb.filter(unmatched), None, None, collector)

    def _emit(self, t, lb, rb, li, ri, collector) -> None:
        """One output batch. With index arrays (matched-pair path) only the
        PROJECTED columns are gathered — Batch.take would copy every column
        including internals, doubling the close cost of a wide expansion."""
        if li is not None:
            n = len(li)
        else:
            n = lb.num_rows if lb is not None else rb.num_rows
        cols: dict[str, np.ndarray] = {}
        for out_name, src in self.left_names:
            if lb is None:
                cols[out_name] = _object_col([None] * n)
            else:
                col = np.asarray(lb[src])
                cols[out_name] = col[li] if li is not None else col
        for out_name, src in self.right_names:
            if rb is None:
                cols[out_name] = _object_col([None] * n)
            else:
                col = np.asarray(rb[src])
                cols[out_name] = col[ri] if ri is not None else col
        cols[TIMESTAMP_FIELD] = np.full(n, t, dtype=np.int64)
        src_keys = lb if lb is not None else rb
        if KEY_FIELD in src_keys:
            k = np.asarray(src_keys.keys)
            cols[KEY_FIELD] = k[li] if (lb is not None and li is not None) else k
        collector.collect(Batch(cols))

    def handle_checkpoint(self, barrier, ctx, collector):
        # in-flight closes are no longer in self.buf: their rows must be
        # emitted before the barrier, not lost from the snapshot
        self._drain_pending(collector, force=True)
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name)
            batches = []
            for t, ent in self.buf.items():
                batches.extend(ent[side])
            tbl.replace_all(batches)
        ctx.table_manager.global_keyed("e").insert(
            ctx.task_info.subtask_index, self.emitted_before
        )


class _StoredRow:
    __slots__ = ("values", "ts", "key", "match_count", "null_emitted")

    def __init__(self, values: tuple, ts: int, key: int):
        self.values = values
        self.ts = ts
        self.key = key
        self.match_count = 0
        self.null_emitted = False


class JoinWithExpiration(Operator):
    """Updating symmetric hash join (reference join_with_expiration.rs:29).

    config: join_type, left_names/right_names: [(out_name, src_name)],
    ttl_micros (buffer retention, default 1 day). Outputs an updating stream
    (_is_retract column); outer sides emit (row, nulls) immediately and
    retract it when a first match arrives.
    """

    def __init__(self, cfg: dict):
        self.join_type: str = cfg.get("join_type", "inner")
        self.left_names: list[tuple[str, str]] = list(cfg["left_names"])
        self.right_names: list[tuple[str, str]] = list(cfg["right_names"])
        self.ttl = int(cfg.get("ttl_micros", 24 * 3600 * 1_000_000))
        # per side: key-hash -> list[_StoredRow]
        self.stores: tuple[dict, dict] = ({}, {})

    def tables(self):
        return [
            TableSpec("left", "expiring_time_key", retention_micros=self.ttl),
            TableSpec("right", "expiring_time_key", retention_micros=self.ttl),
        ]

    def _outer_for(self, side: int) -> bool:
        """Does `side` emit null-padded rows when unmatched?"""
        return self.join_type == "full" or self.join_type == (
            "left" if side == 0 else "right"
        )

    def _src_names(self, side: int) -> list[tuple[str, str]]:
        return self.left_names if side == 0 else self.right_names

    # ------------------------------------------------------------------

    def on_start(self, ctx):
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name, self.ttl)
            store = self.stores[side]
            for b in tbl.all_batches():
                keys = b.keys.astype(np.uint64).view(np.int64)
                srcs = [src for _o, src in self._src_names(side)]
                mc = b["__match_count"]
                ne = b["__null_emitted"].astype(bool)
                for j in range(b.num_rows):
                    row = _StoredRow(
                        tuple(b[s][j] for s in srcs), int(b.timestamps[j]), int(keys[j])
                    )
                    row.match_count = int(mc[j])
                    row.null_emitted = bool(ne[j])
                    store.setdefault(int(keys[j]), []).append(row)
            tbl.replace_all([])

    # ------------------------------------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0):
        side = ctx.edge_of_input(input_index)
        other = 1 - side
        n = batch.num_rows
        keys = batch.keys.astype(np.uint64).view(np.int64)
        ts = batch.timestamps
        retracts = (
            np.asarray(batch[IS_RETRACT_FIELD], dtype=bool)
            if IS_RETRACT_FIELD in batch
            else np.zeros(n, dtype=bool)
        )
        srcs = [src for _o, src in self._src_names(side)]
        src_cols = [np.asarray(batch[s]) for s in srcs]
        out_rows: list[tuple[tuple, tuple, int, bool]] = []  # (lvals, rvals, ts, retract)
        my_store = self.stores[side]
        other_store = self.stores[other]
        for j in range(n):
            k = int(keys[j])
            vals = tuple(c[j] for c in src_cols)
            t = int(ts[j])
            matches = other_store.get(k, [])
            if not retracts[j]:
                row = _StoredRow(vals, t, k)
                my_store.setdefault(k, []).append(row)
                row.match_count = len(matches)
                for m in matches:
                    if m.match_count == 0 and m.null_emitted:
                        # first match for an outer-side row: retract its nulls
                        out_rows.append(self._pad(other, m.values, max(m.ts, t), True))
                        m.null_emitted = False
                    m.match_count += 1
                    out_rows.append(self._pair(side, vals, m.values, max(m.ts, t), False))
                if not matches and self._outer_for(side):
                    out_rows.append(self._pad(side, vals, t, False))
                    row.null_emitted = True
            else:
                # retract: remove the stored row with equal values
                lst = my_store.get(k, [])
                found = None
                for i, r in enumerate(lst):
                    if r.values == vals:
                        found = i
                        break
                if found is None:
                    raise RuntimeError(
                        "retract for a row never seen (updating join ordering violation)"
                    )
                row = lst.pop(found)
                if not lst:
                    my_store.pop(k, None)
                if row.null_emitted:
                    out_rows.append(self._pad(side, vals, t, True))
                else:
                    for m in matches:
                        m.match_count -= 1
                        out_rows.append(self._pair(side, vals, m.values, max(m.ts, t), True))
                        if m.match_count == 0 and self._outer_for(other):
                            out_rows.append(self._pad(other, m.values, max(m.ts, t), False))
                            m.null_emitted = True
        if out_rows:
            self._emit(out_rows, collector)

    def _pair(self, side, vals, other_vals, ts, retract):
        if side == 0:
            return (vals, other_vals, ts, retract)
        return (other_vals, vals, ts, retract)

    def _pad(self, side, vals, ts, retract):
        if side == 0:
            return (vals, None, ts, retract)
        return (None, vals, ts, retract)

    def _emit(self, out_rows, collector) -> None:
        n = len(out_rows)
        cols: dict[str, np.ndarray] = {}
        n_l = len(self.left_names)
        for i, (out_name, _src) in enumerate(self.left_names):
            cols[out_name] = _object_col(
                [lv[i] if lv is not None else None for lv, _r, _t, _x in out_rows]
            )
        for i, (out_name, _src) in enumerate(self.right_names):
            cols[out_name] = _object_col(
                [rv[i] if rv is not None else None for _l, rv, _t, _x in out_rows]
            )
        cols[IS_RETRACT_FIELD] = np.array([r for _l, _r, _t, r in out_rows], dtype=bool)
        cols[TIMESTAMP_FIELD] = np.array([t for _l, _r, t, _x in out_rows], dtype=np.int64)
        collector.collect(Batch(cols))

    # ------------------------------------------------------------------

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            return watermark
        cutoff = watermark.value - self.ttl
        oldest = None
        for store in self.stores:
            dead_keys = []
            for k, lst in store.items():
                lst[:] = [r for r in lst if r.ts >= cutoff]
                if not lst:
                    dead_keys.append(k)
                else:
                    for r in lst:
                        if oldest is None or r.ts < oldest:
                            oldest = r.ts
            for k in dead_keys:
                del store[k]
        # future emissions carry ts = max(sides) >= the oldest buffered row;
        # hold the watermark to that bound so downstream never sees late rows
        held = watermark.value if oldest is None else min(watermark.value, oldest)
        from ..types import Watermark

        return Watermark.event_time(held)

    def handle_checkpoint(self, barrier, ctx, collector):
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name, self.ttl)
            store = self.stores[side]
            rows = [r for lst in store.values() for r in lst]
            if not rows:
                tbl.replace_all([])
                continue
            srcs = [src for _o, src in self._src_names(side)]
            cols: dict[str, np.ndarray] = {
                TIMESTAMP_FIELD: np.array([r.ts for r in rows], dtype=np.int64),
                KEY_FIELD: np.array([r.key for r in rows], dtype=np.int64).view(np.uint64),
                "__match_count": np.array([r.match_count for r in rows], dtype=np.int64),
                "__null_emitted": np.array([r.null_emitted for r in rows], dtype=bool),
            }
            for i, s in enumerate(srcs):
                cols[s] = _object_col([r.values[i] for r in rows])
            tbl.replace_all([Batch(cols)])


class LookupJoin(Operator):
    """config: connector (object with lookup(keys)->dict, from the connector
    registry), key_exprs: [Expr] evaluated on the stream, right_names:
    [(out_name, field)] columns pulled from the looked-up row, join_type:
    inner|left, cache_ttl_micros, cache_max_size, max_concurrency.

    Async pipelined lookups (reference lookup_join.rs:35): cache misses are
    batched per input batch and dispatched to a bounded thread pool off the
    task thread; batches emit strictly in input order as their fetches land,
    and watermarks/barriers drain everything in flight first, so a slow
    lookup source overlaps N fetches instead of serializing the hot loop."""

    def __init__(self, cfg: dict):
        from collections import deque

        self.connector = cfg["connector"]
        self.key_exprs = list(cfg["key_exprs"])
        self.right_names: list[tuple[str, str]] = list(cfg["right_names"])
        self.join_type = cfg.get("join_type", "left")
        self.cache_ttl = int(cfg.get("cache_ttl_micros", 60_000_000))
        self.cache_max = int(cfg.get("cache_max_size", 100_000))
        self.max_concurrency = int(cfg.get("max_concurrency", 16))
        self.cache: dict = {}  # key -> (row|None, wall_micros)
        self._pool = None
        # FIFO of ("batch", batch, keys, resolved, missing, fut, borrowed)
        # and ("wm", Watermark) markers: strictly ordered emission
        self._pending = deque()
        # key -> in-flight Future: concurrent batches borrow a pending
        # fetch instead of re-asking the source for the same key
        self._inflight: dict = {}

    def tables(self):
        return [TableSpec("c", "global_keyed")]

    def on_start(self, ctx):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=self.max_concurrency, thread_name_prefix="lookup-join")

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        key_cols = [
            np.asarray(eval_expr(e, batch.columns, n)) for e in self.key_exprs
        ]
        keys = [
            tuple(c[i] for c in key_cols) if len(key_cols) > 1 else key_cols[0][i]
            for i in range(n)
        ]
        now = int(_time.time() * 1e6)
        # resolve hits AT SUBMIT TIME: deferred emission must not depend on
        # cache entries that a later eviction sweep could remove
        resolved: dict = {}
        missing: list = []
        borrowed: dict = {}
        for k in set(keys):
            ent = self.cache.get(k)
            if ent is not None and now - ent[1] <= self.cache_ttl:
                resolved[k] = ent[0]
            elif k in self._inflight:
                borrowed[k] = self._inflight[k]
            else:
                missing.append(k)
        fut = None
        if missing:
            if self._pool is None:
                self.on_start(ctx)
            fut = self._pool.submit(self.connector.lookup, missing)
            for k in missing:
                self._inflight[k] = fut
        self._pending.append(("batch", batch, keys, resolved, missing, fut, borrowed))
        self._drain(collector, block=False)
        # backpressure: bound in-flight batches so a stalled source cannot
        # queue unbounded memory behind the pool
        while sum(1 for e in self._pending if e[0] == "batch") > 2 * self.max_concurrency:
            self._emit_head(collector)

    def _head_ready(self) -> bool:
        e = self._pending[0]
        if e[0] == "wm":
            return True
        fut, borrowed = e[5], e[6]
        if fut is not None and not fut.done():
            return False
        return all(f.done() for f in borrowed.values())

    def _drain(self, collector, block: bool) -> None:
        while self._pending:
            if not block and not self._head_ready():
                return
            self._emit_head(collector)

    def _emit_head(self, collector) -> None:
        entry = self._pending.popleft()
        if entry[0] == "wm":
            from ..types import Signal

            collector.broadcast(Signal.watermark_of(entry[1]))
            return
        _tag, batch, keys, resolved, missing, fut, borrowed = entry
        now = int(_time.time() * 1e6)
        val_of = dict(resolved)
        if fut is not None:
            fetched = fut.result()
            for k in missing:
                val_of[k] = fetched.get(k)
                self.cache[k] = (fetched.get(k), now)
                if self._inflight.get(k) is fut:
                    del self._inflight[k]
        for k, bf in borrowed.items():
            val_of[k] = bf.result().get(k)
        rows = [val_of[k] for k in keys]
        if len(self.cache) > self.cache_max:
            # evict oldest entries — after gathering, so this batch's keys
            # cannot be evicted before they are read
            by_age = sorted(self.cache.items(), key=lambda kv: kv[1][1])
            for k, _ in by_age[: len(self.cache) - self.cache_max]:
                del self.cache[k]
        n = batch.num_rows
        present = np.array([r is not None for r in rows], dtype=bool)
        if self.join_type == "inner" and not present.all():
            batch = batch.filter(present)
            rows = [r for r, p in zip(rows, present) if p]
            present = present[present]
            n = batch.num_rows
            if n == 0:
                return
        cols = dict(batch.columns)
        for out_name, field in self.right_names:
            vals = [r.get(field) if r is not None else None for r in rows]
            sample = next((v for v in vals if v is not None), None)
            if isinstance(sample, (str, type(None))) or not present.all():
                cols[out_name] = _object_col(vals)
            else:
                cols[out_name] = np.array(vals)
        collector.collect(Batch(cols))

    def handle_watermark(self, watermark, ctx, collector):
        # watermark-held ordered emission WITHOUT stalling the pipeline:
        # the watermark queues behind its preceding batches and broadcasts
        # as the queue drains (same shape as TumblingAggregate's pending
        # queue) — blocking here would cap lookup overlap at one batch,
        # since upstream emits a watermark after nearly every batch
        self._drain(collector, block=False)
        if not self._pending:
            return watermark
        self._pending.append(("wm", watermark))
        return None

    def handle_checkpoint(self, barrier, ctx, collector):
        self._drain(collector, block=True)

    def on_close(self, ctx, collector):
        self._drain(collector, block=True)
        if self._pool is not None:
            self._pool.shutdown(wait=False)


@register_operator(OpName.INSTANT_JOIN)
def _make_instant(cfg: dict):
    return InstantJoin(cfg)


@register_operator(OpName.JOIN_WITH_EXPIRATION)
def _make_expiring(cfg: dict):
    return JoinWithExpiration(cfg)


@register_operator(OpName.LOOKUP_JOIN)
def _make_lookup(cfg: dict):
    return LookupJoin(cfg)
