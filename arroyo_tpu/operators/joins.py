"""Join operators.

- InstantJoin: windowed stream-stream join (reference:
  crates/arroyo-worker/src/arrow/instant_join.rs:38). Upstream window
  aggregates stamp each row with its window start, so both inputs arrive
  bucketed by exact timestamp; rows buffer per timestamp and the join for
  bucket t executes when the merged watermark passes t. Vectorized hash join
  on the routing-key column (both sides are keyed on the equi-join columns,
  so equal keys share a hash; hashes are 64-bit and collision-checked by the
  planner's key columns being carried through).
- JoinWithExpiration: updating non-windowed join (reference:
  join_with_expiration.rs:29) — symmetric hash join over TTL'd key-time
  buffers, emitting retract/append pairs so outer joins stay consistent as
  matches appear and disappear.
- LookupJoin: stream enriched against an external keyed table through a
  lookup connector with a TTL'd cache (reference: lookup_join.rs:35).
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec, persist_mark, restore_marks
from ..types import Signal
from .updating_aggregate import IS_RETRACT_FIELD


def _object_col(values) -> np.ndarray:
    """Object column from arbitrary python values in one shot (np.fromiter;
    the per-element assignment loop this replaces re-allocated and filled
    element-wise on every emitted batch of the wide-expansion path)."""
    vals = values if isinstance(values, (list, tuple)) else list(values)
    return np.fromiter(vals, dtype=object, count=len(vals))


_null_cache = np.empty(0, dtype=object)


def _null_col(n: int) -> np.ndarray:
    """All-None object column, served as a view of one shared buffer and
    reused across ``_emit`` calls (emitted columns are never mutated in
    place downstream — filter/take/concat all copy)."""
    global _null_cache
    if len(_null_cache) < n:
        _null_cache = np.empty(max(n, 2 * len(_null_cache), 1024), dtype=object)
    return _null_cache[:n]


def _jax_on_host_cpu() -> bool:
    """True when the "device" backend would just run on the host CPU via
    jax — there a device dispatch costs more than the numpy probe it
    replaces (measured ~4x at q8 window sizes), so the join stays on
    numpy unless ``device.force-device-join`` forces the device path
    (tests)."""
    from ..config import config

    if config().get("device.force-device-join"):
        return False
    global _jax_cpu
    if _jax_cpu is None:
        try:
            import jax

            _jax_cpu = jax.default_backend() == "cpu"
        except Exception:  # noqa: BLE001 - no jax at all: host numpy it is
            _jax_cpu = True
    return _jax_cpu


_jax_cpu: Optional[bool] = None


# the sort/search probe now lives beside its device twin (ops/join_probe);
# this alias keeps the historic name importable
from ..ops.join_probe import host_join_indices as _hash_join_indices  # noqa: E402


class InstantJoin(Operator):
    """config: join_type: inner|left|right|full, left_names/right_names:
    [(out_name, src_name)] column selections per side, backend override
    "jax"|"numpy"|None (default: device when enabled).

    Device lowering: the sort/search phase of each window's join runs on
    the device (ops/join_probe.py) and its result streams back while later
    batches keep flowing — closes queue in order and each watermark is
    forwarded only after its windows' rows, the same pipelining discipline
    as the window aggregates."""

    def __init__(self, cfg: dict):
        from ..config import config

        self.join_type: str = cfg.get("join_type", "inner")
        self.left_names: list[tuple[str, str]] = list(cfg["left_names"])
        self.right_names: list[tuple[str, str]] = list(cfg["right_names"])
        self.backend = cfg.get("backend") or (
            "jax" if config().get("device.enabled") else "numpy"
        )
        # below this many rows on either side, the numpy join is cheaper
        # than a device dispatch
        self.device_min_rows = int(config().get("device.join-min-rows", 2048))
        # t -> [left batches], [right batches]
        self.buf: dict[int, tuple[list, list]] = {}
        self.late_rows = 0  # state: ephemeral — observability counter (obs/profile.py export); never read into emitted data
        self.emitted_before: Optional[int] = None
        # in-flight closes: (JoinHandle|None, t, lb, rb, Watermark|None)
        self._pending: deque = deque()  # state: ephemeral — force-drained at every barrier (handle_checkpoint) before the snapshot

    def tables(self):
        return [
            TableSpec("left", "expiring_time_key"),
            TableSpec("right", "expiring_time_key"),
            TableSpec("e", "global_keyed"),  # late-data barrier
        ]

    def on_start(self, ctx):
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name)
            for b in tbl.all_batches():
                self._buffer(b, side)
            tbl.replace_all([])
        barriers = restore_marks(ctx, "e")
        if barriers:
            self.emitted_before = max(barriers)

    def _buffer(self, batch: Batch, side: int) -> None:
        """One split per incoming batch: the per-unique-timestamp
        ``filter(ts == t)`` this replaces rescanned the full column once per
        window (O(uniq*n)). Upstream window stamping emits time-ordered
        batches, so the common case needs no sort at all — per-timestamp
        runs are already contiguous and stored as zero-copy slices; only a
        genuinely unordered batch pays one stable argsort."""
        ts = batch.timestamps
        n = len(ts)
        if n == 0:
            return
        d = np.diff(ts)
        if len(d) == 0 or not (d < 0).any():
            sorted_b, sts = batch, ts
        else:
            order = np.argsort(ts, kind="stable")
            sorted_b = batch.take(order)
            sts = ts[order]
            d = np.diff(sts)
        if n == 1 or not (d > 0).any():
            self.buf.setdefault(int(sts[0]), ([], []))[side].append(sorted_b)
            return
        bounds = np.concatenate(([0], np.flatnonzero(d > 0) + 1, [n]))
        for i in range(len(bounds) - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            ent = self.buf.setdefault(int(sts[lo]), ([], []))
            piece = sorted_b.slice(lo, hi)
            if 4 * (hi - lo) <= n:
                # a small view would pin the whole parent batch's columns
                # until this window closes; materialize it instead
                piece = Batch({k: v.copy() for k, v in piece.columns.items()})
            ent[side].append(piece)

    def process_batch(self, batch, ctx, collector, input_index=0):
        if self._pending:
            self._drain_pending(collector)
        side = ctx.edge_of_input(input_index)
        if self.emitted_before is not None:
            late = batch.timestamps < self.emitted_before
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
        self._buffer(batch, side)

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            self._drain_pending(collector, force=True)
            return watermark
        scheduled = self._schedule_closed(watermark.value, watermark, collector)
        self._drain_pending(collector)
        if scheduled or self._pending:
            return None  # watermark rides the pending queue, in order
        return watermark

    def on_close(self, ctx, collector):
        self._schedule_closed(None, None, collector)
        self._drain_pending(collector, force=True)

    def _schedule_closed(self, before: Optional[int], wm, collector) -> bool:
        """Queue the join for every window closed by the watermark; the
        watermark marker is appended after its windows so emission order is
        preserved. Returns True when anything was queued.

        When one watermark closes SEVERAL buffered windows (catch-up after a
        gap, end-of-stream), the per-window pipeline would emit N tiny
        batches each paying full collector/queue overhead; the fused path
        concatenates the sides, probes once partitioned by window, and emits
        one coalesced batch per match category instead."""
        ts_list = sorted(t for t in self.buf if before is None or t < before)
        if len(ts_list) > 1 and (self.backend != "jax" or _jax_on_host_cpu()):
            # host-probe backends only: on a real accelerator the per-window
            # pipelined device closes below stay in charge (their async
            # dispatch hides probe latency, and the collector's coalescing
            # still merges the small per-window output batches), so fusing
            # must not silently demote the heaviest closes to the host.
            # Earlier in-flight closes (and their held watermarks) must
            # drain first so emission order is preserved.
            self._drain_pending(collector, force=True)
            self._fused_close(ts_list, collector)
            if before is not None and (
                self.emitted_before is None or before > self.emitted_before
            ):
                self.emitted_before = before
            return False  # rows already emitted; the watermark may forward
        for t in ts_list:
            left, right = self.buf.pop(t)
            while len(self._pending) >= 16:  # bound in-flight joins
                handle, pt, lb, rb, pwm = self._pending.popleft()
                if pwm is not None:
                    collector.broadcast(Signal.watermark_of(pwm))
                else:
                    self._join_and_emit(pt, lb, rb, handle, collector)
            self._pending.append(self._start_join(t, left, right))
        if before is not None and (
            self.emitted_before is None or before > self.emitted_before
        ):
            self.emitted_before = before
        if wm is not None:
            if self._pending or ts_list:
                self._pending.append((None, None, None, None, wm))
                return True
            return False
        return bool(ts_list)

    def _start_join(self, t: int, left: list, right: list):
        lb = Batch.concat(left) if left else None
        rb = Batch.concat(right) if right else None
        handle = None
        if lb is not None and rb is not None:
            n = max(lb.num_rows, rb.num_rows)
            if (self.backend == "jax" and n >= self.device_min_rows
                    and not _jax_on_host_cpu()):
                from ..ops.join_probe import device_join_start

                lk = lb.keys.astype(np.uint64).view(np.int64)
                rk = rb.keys.astype(np.uint64).view(np.int64)
                handle = device_join_start(lk, rk)
        return (handle, t, lb, rb, None)

    def _fused_close(self, ts_list: list, collector) -> None:
        """Close every window in ts_list as ONE join: single probe over the
        concatenated sides partitioned by window, one output batch per match
        category (inner pairs / left pads / right pads) instead of N
        per-window emits. Rows carry their own window timestamps, so the
        emitted groups are identical to per-window closes."""
        from ..ops.join_probe import fused_join_indices

        jt = self.join_type
        lbs: dict[int, Batch] = {}
        rbs: dict[int, Batch] = {}
        for t in ts_list:
            left, right = self.buf.pop(t)
            if left:
                lbs[t] = Batch.concat(left)
            if right:
                rbs[t] = Batch.concat(right)
        both = [t for t in ts_list if t in lbs and t in rbs]
        if both:
            lb = Batch.concat([lbs[t] for t in both])
            rb = Batch.concat([rbs[t] for t in both])
            l_bounds = np.cumsum([0] + [lbs[t].num_rows for t in both])
            r_bounds = np.cumsum([0] + [rbs[t].num_rows for t in both])
            lk = lb.keys.astype(np.uint64).view(np.int64)
            rk = rb.keys.astype(np.uint64).view(np.int64)
            li, ri = fused_join_indices(lk, rk, l_bounds, r_bounds)
            if len(li):
                self._emit(None, lb, rb, li, ri, collector)
            if jt in ("left", "full"):
                unmatched = np.ones(lb.num_rows, dtype=bool)
                unmatched[li] = False
                if unmatched.any():
                    self._emit(None, lb.filter(unmatched), None, None, None, collector)
            if jt in ("right", "full"):
                unmatched = np.ones(rb.num_rows, dtype=bool)
                unmatched[ri] = False
                if unmatched.any():
                    self._emit(None, None, rb.filter(unmatched), None, None, collector)
        if jt in ("left", "full"):
            lonely = [t for t in ts_list if t in lbs and t not in rbs]
            if lonely:
                self._emit(None, Batch.concat([lbs[t] for t in lonely]),
                           None, None, None, collector)
        if jt in ("right", "full"):
            lonely = [t for t in ts_list if t in rbs and t not in lbs]
            if lonely:
                self._emit(None, None, Batch.concat([rbs[t] for t in lonely]),
                           None, None, collector)

    def _drain_pending(self, collector, force: bool = False) -> None:
        while self._pending:
            handle, t, lb, rb, wm = self._pending[0]
            if wm is None and handle is not None and not force and not handle.is_ready():
                return
            self._pending.popleft()
            if wm is not None:
                collector.broadcast(Signal.watermark_of(wm))
                continue
            self._join_and_emit(t, lb, rb, handle, collector)

    def _join_and_emit(self, t: int, lb, rb, handle, collector) -> None:
        jt = self.join_type
        if lb is None and rb is None:
            return
        if lb is None:
            if jt in ("right", "full"):
                self._emit(t, None, rb, None, None, collector)
            return
        if rb is None:
            if jt in ("left", "full"):
                self._emit(t, lb, None, None, None, collector)
            return
        if handle is not None:
            li, ri = handle.result()
        else:
            lk = lb.keys.astype(np.uint64).view(np.int64)
            rk = rb.keys.astype(np.uint64).view(np.int64)
            li, ri = _hash_join_indices(lk, rk)
        if len(li):
            self._emit(t, lb, rb, li, ri, collector)
        if jt in ("left", "full"):
            unmatched = np.ones(lb.num_rows, dtype=bool)
            unmatched[li] = False
            if unmatched.any():
                self._emit(t, lb.filter(unmatched), None, None, None, collector)
        if jt in ("right", "full"):
            unmatched = np.ones(rb.num_rows, dtype=bool)
            unmatched[ri] = False
            if unmatched.any():
                self._emit(t, None, rb.filter(unmatched), None, None, collector)

    def _emit(self, t, lb, rb, li, ri, collector) -> None:
        """One output batch. With index arrays (matched-pair path) only the
        PROJECTED columns are gathered — Batch.take would copy every column
        including internals, doubling the close cost of a wide expansion.
        ``t``: the window start, or None for the fused multi-window path
        where each row carries its own window timestamp already."""
        if li is not None:
            n = len(li)
        else:
            n = lb.num_rows if lb is not None else rb.num_rows
        cols: dict[str, np.ndarray] = {}
        for out_name, src in self.left_names:
            if lb is None:
                cols[out_name] = _null_col(n)
            else:
                col = np.asarray(lb[src])
                cols[out_name] = col[li] if li is not None else col
        for out_name, src in self.right_names:
            if rb is None:
                cols[out_name] = _null_col(n)
            else:
                col = np.asarray(rb[src])
                cols[out_name] = col[ri] if ri is not None else col
        if t is not None:
            cols[TIMESTAMP_FIELD] = np.full(n, t, dtype=np.int64)
        else:
            src_ts = (lb if lb is not None else rb).timestamps
            cols[TIMESTAMP_FIELD] = (
                src_ts[li] if (lb is not None and li is not None) else src_ts)
        src_keys = lb if lb is not None else rb
        if KEY_FIELD in src_keys:
            k = np.asarray(src_keys.keys)
            cols[KEY_FIELD] = k[li] if (lb is not None and li is not None) else k
        collector.collect(Batch(cols))

    def handle_checkpoint(self, barrier, ctx, collector):
        # in-flight closes are no longer in self.buf: their rows must be
        # emitted before the barrier, not lost from the snapshot
        self._drain_pending(collector, force=True)
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name)
            batches = []
            # sorted: snapshot row order feeds _buffer's per-window lists at
            # restore, so it must not depend on buf's insertion history
            for t in sorted(self.buf):
                batches.extend(self.buf[t][side])
            tbl.replace_all(batches)
        persist_mark(ctx, "e", self.emitted_before)


class _SideStore:
    """Columnar buffer of one join side's live rows (amortized-growth
    arrays, dead rows masked then compacted): the vectorized probe target
    that replaced JoinWithExpiration's per-row dict-of-_StoredRow store."""

    __slots__ = ("n", "cap", "keys", "ts", "match_count", "null_emitted",
                 "alive", "vals", "n_dead")

    def __init__(self, n_vals: int, cap: int = 1024):
        self.n = 0
        self.cap = cap
        self.keys = np.empty(cap, dtype=np.int64)
        self.ts = np.empty(cap, dtype=np.int64)
        self.match_count = np.empty(cap, dtype=np.int64)
        self.null_emitted = np.empty(cap, dtype=bool)
        self.alive = np.zeros(cap, dtype=bool)
        self.vals = [np.empty(cap, dtype=object) for _ in range(n_vals)]
        self.n_dead = 0

    def _grow(self, need: int) -> None:
        cap = self.cap
        while cap < self.n + need:
            cap *= 2
        for name in ("keys", "ts", "match_count", "null_emitted", "alive"):
            old = getattr(self, name)
            new = (np.zeros(cap, dtype=old.dtype) if name == "alive"
                   else np.empty(cap, dtype=old.dtype))
            new[: self.n] = old[: self.n]
            setattr(self, name, new)
        for i, old in enumerate(self.vals):
            new = np.empty(cap, dtype=object)
            new[: self.n] = old[: self.n]
            self.vals[i] = new
        self.cap = cap

    def append(self, keys: np.ndarray, ts: np.ndarray, vals: list,
               match_count: np.ndarray, null_emitted) -> np.ndarray:
        k = len(keys)
        if self.n + k > self.cap:
            self._grow(k)
        lo, hi = self.n, self.n + k
        self.keys[lo:hi] = keys
        self.ts[lo:hi] = ts
        self.match_count[lo:hi] = match_count
        self.null_emitted[lo:hi] = null_emitted
        self.alive[lo:hi] = True
        for col, v in zip(self.vals, vals):
            col[lo:hi] = v
        self.n = hi
        return np.arange(lo, hi, dtype=np.int64)

    def live_ids(self) -> np.ndarray:
        return np.flatnonzero(self.alive[: self.n])

    def kill(self, ids) -> None:
        self.alive[ids] = False
        self.n_dead += np.size(ids) if not isinstance(ids, (int, np.integer)) else 1
        if self.n_dead > max(1024, self.n - self.n_dead):
            self.compact()

    def compact(self) -> None:
        keep = self.live_ids()
        m = len(keep)
        self.keys[:m] = self.keys[keep]
        self.ts[:m] = self.ts[keep]
        self.match_count[:m] = self.match_count[keep]
        self.null_emitted[:m] = self.null_emitted[keep]
        for col in self.vals:
            col[:m] = col[keep]
        self.alive[:m] = True
        self.alive[m: self.n] = False
        self.n = m
        self.n_dead = 0


class JoinWithExpiration(Operator):
    """Updating symmetric hash join (reference join_with_expiration.rs:29).

    config: join_type, left_names/right_names: [(out_name, src_name)],
    ttl_micros (buffer retention, default 1 day). Outputs an updating stream
    (_is_retract column); outer sides emit (row, nulls) immediately and
    retract it when a first match arrives.

    The buffering/probe hot path is columnar: appends probe the other
    side's _SideStore with the shared sort/search join (host_join_indices)
    and update match counts with one scatter-add; only retract rows — which
    must locate one stored row by full value equality — walk rows in
    Python, and they arrive rarely and in small numbers.
    """

    def __init__(self, cfg: dict):
        from ..state.spill import spill_enabled

        self.join_type: str = cfg.get("join_type", "inner")
        self.left_names: list[tuple[str, str]] = list(cfg["left_names"])
        self.right_names: list[tuple[str, str]] = list(cfg["right_names"])
        self.ttl = int(cfg.get("ttl_micros", 24 * 3600 * 1_000_000))
        self.stores: tuple[_SideStore, _SideStore] = (
            _SideStore(len(self.left_names)), _SideStore(len(self.right_names)))
        # TTL-expired buffered rows dropped from the side stores, exported
        # as arroyo_late_rows_total (counting only — expiry semantics are
        # unchanged)
        self.late_rows = 0  # state: ephemeral — observability counter (obs/profile.py export); never read into emitted data
        # tiered state (state/spill.py): cold side-store rows (oldest event
        # times) spill as bloom/zone-mapped runs; a probe that hits a
        # spilled key promotes its rows back into the live store first, so
        # the join logic itself never changes
        self._spill = spill_enabled()
        self._annexes = None  # (RowSpillAnnex, RowSpillAnnex) in on_start

    def tables(self):
        return [
            TableSpec("left", "expiring_time_key", retention_micros=self.ttl),
            TableSpec("right", "expiring_time_key", retention_micros=self.ttl),
            TableSpec("left__spill", "global_keyed"),
            TableSpec("right__spill", "global_keyed"),
        ]

    def _outer_for(self, side: int) -> bool:
        """Does `side` emit null-padded rows when unmatched?"""
        return self.join_type == "full" or self.join_type == (
            "left" if side == 0 else "right"
        )

    def state_sizes(self) -> dict[str, tuple[int, int]]:
        """Live rows + approximate bytes per side store (obs/profile.py
        state gauges): between barriers the host tables lag this columnar
        state, so the live view overrides them."""
        out: dict[str, tuple[int, int]] = {}
        for side, name in ((0, "left"), (1, "right")):
            store = self.stores[side]
            live = store.n - store.n_dead
            # keys/ts/match_count int64 lanes + two bool lanes + one object
            # pointer per value column (payload bytes live behind pointers;
            # the gauge is a floor, which is the safe direction for spill)
            per_row = 8 * (3 + len(store.vals)) + 2
            out[name] = (live, live * per_row)
        return out

    def _src_names(self, side: int) -> list[tuple[str, str]]:
        return self.left_names if side == 0 else self.right_names

    # ------------------------------------------------------------------

    def on_start(self, ctx):
        if self._spill:
            from ..state.spill import (RowSpillAnnex, SpillStats,
                                       restore_manifest)

            stats = SpillStats()  # one shared stats block for both sides
            self._annexes = tuple(
                RowSpillAnnex(ctx.task_info, ctx.table_manager.storage_url,
                              name, len(self._src_names(side)), stats)
                for side, name in ((0, "left"), (1, "right")))
            self._annexes[0].adopt(restore_manifest(ctx, "left__spill"))
            self._annexes[1].adopt(restore_manifest(ctx, "right__spill"))
        else:
            from ..state.spill import require_spill_for_manifest

            # spilled side-store rows exist only in run files: restoring
            # with spilling disabled must fail loudly, not silently drop
            # buffered join state
            require_spill_for_manifest(ctx, "left__spill")
            require_spill_for_manifest(ctx, "right__spill")
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name, self.ttl)
            store = self.stores[side]
            srcs = [src for _o, src in self._src_names(side)]
            for b in tbl.all_batches():
                if b.num_rows == 0:
                    continue
                store.append(
                    b.keys.astype(np.uint64).view(np.int64),
                    b.timestamps,
                    [_object_col(np.asarray(b[s])) for s in srcs],
                    np.asarray(b["__match_count"], dtype=np.int64),
                    np.asarray(b["__null_emitted"], dtype=bool),
                )
            tbl.replace_all([])

    # ------------------------------------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0):
        side = ctx.edge_of_input(input_index)
        n = batch.num_rows
        keys = batch.keys.astype(np.uint64).view(np.int64)
        ts = batch.timestamps
        retracts = (
            np.asarray(batch[IS_RETRACT_FIELD], dtype=bool)
            if IS_RETRACT_FIELD in batch
            else None
        )
        if self._annexes is not None:
            # any spilled row this batch's keys could touch promotes back
            # into the live store FIRST (match counts and null pads mutate,
            # and runs are immutable), so the probe/retract logic below is
            # byte-identical to the fully-resident path
            self._promote(1 - side, keys)
            if retracts is not None and retracts.any():
                self._promote(side, keys[retracts])
        srcs = [src for _o, src in self._src_names(side)]
        src_cols = [np.asarray(batch[s]) for s in srcs]
        out: list[tuple] = []  # emission segments, in order
        if retracts is None or not retracts.any():
            self._append_run(side, keys, ts, src_cols, out)
        else:
            # preserve in-batch ordering: vectorize each contiguous run of
            # appends, walk retract rows one by one (they must locate one
            # stored row by exact value equality)
            edges = np.flatnonzero(np.diff(retracts)) + 1
            for lo, hi in zip(np.r_[0, edges], np.r_[edges, n]):
                lo, hi = int(lo), int(hi)
                if retracts[lo]:
                    for j in range(lo, hi):
                        self._retract_row(
                            side, int(keys[j]), int(ts[j]),
                            tuple(c[j] for c in src_cols), out)
                else:
                    self._append_run(side, keys[lo:hi], ts[lo:hi],
                                     [c[lo:hi] for c in src_cols], out)
        if out:
            self._emit(out, collector)

    def _promote(self, side: int, keys: np.ndarray) -> None:
        """Pull every alive spilled row of ``side`` whose key appears in
        ``keys`` back into the live store (bloom/zone pruned)."""
        annex = self._annexes[side]
        if not annex.has_runs() or not len(keys):
            return
        seg = annex.probe(keys)
        if seg is not None:
            k, t, mc, ne, vals = seg
            self.stores[side].append(k, t, vals, mc, ne)

    def spill_stats(self):
        if self._annexes is None:
            return None
        stats = self._annexes[0].stats  # shared by both sides
        cold = sum(1 for a in self._annexes if a.has_runs())
        return {"bytes_total": stats.bytes_total, "hot": 2 - cold,
                "cold": cold, "probe_files": stats.probe_files}

    def _maybe_spill(self) -> None:
        """Budget enforcement across BOTH side stores: the globally oldest
        rows (event time, then side/position as the deterministic
        tie-break) spill first, down to the low-water mark."""
        from ..config import config
        from ..state.spill import spill_budget_bytes

        if self._annexes is None:
            return
        sizes = self.state_sizes()
        total = sum(b for _r, b in sizes.values())
        budget = spill_budget_bytes()
        if total <= budget:
            return
        target = budget * float(config().get("state.spill.headroom", 0.75))
        parts = []
        for s in (0, 1):
            live = self.stores[s].live_ids()
            if len(live):
                parts.append((self.stores[s].ts[live],
                              np.full(len(live), s, dtype=np.int64), live))
        if not parts:
            return
        ts_all = np.concatenate([p[0] for p in parts])
        side_all = np.concatenate([p[1] for p in parts])
        ids_all = np.concatenate([p[2] for p in parts])
        per_row = max(8 * (3 + len(st.vals)) + 2 for st in self.stores)
        k = min(len(ts_all), int((total - target) / max(per_row, 1)) + 1)
        pick = np.lexsort((ids_all, side_all, ts_all))[:k]
        for s in (0, 1):
            sel = ids_all[pick[side_all[pick] == s]]
            if not len(sel):
                continue
            store = self.stores[s]
            ok = self._annexes[s].spill_rows(
                store.keys[sel], store.ts[sel], store.match_count[sel],
                store.null_emitted[sel], [c[sel] for c in store.vals])
            if ok:
                store.kill(sel)

    def _append_run(self, side: int, keys, ts, src_cols, out: list) -> None:
        """Vectorized append path: probe the other side once, scatter-add
        match counts, emit pairs/pads as columnar segments."""
        other = self.stores[1 - side]
        mine = self.stores[side]
        live = other.live_ids()
        if len(live):
            bi, oi = _hash_join_indices(keys, other.keys[live])
            oid = live[oi]
        else:
            bi = oid = np.empty(0, dtype=np.int64)
        counts = np.bincount(bi, minlength=len(keys)) if len(bi) else \
            np.zeros(len(keys), dtype=np.int64)
        new_ids = mine.append(keys, ts, src_cols, counts, False)
        if len(oid):
            # store rows seeing their FIRST match: retract their null pads.
            # pairs are ordered by probe row asc, so the first occurrence of
            # a store id carries the earliest matching row's timestamp
            uniq, first = np.unique(oid, return_index=True)
            newly = (other.match_count[uniq] == 0) & other.null_emitted[uniq]
            if newly.any():
                ids = uniq[newly]
                pad_ts = np.maximum(other.ts[ids], ts[bi[first[newly]]])
                out.append(self._pad_seg(1 - side,
                                         [c[ids] for c in other.vals],
                                         pad_ts, True))
                other.null_emitted[ids] = False
            np.add.at(other.match_count, oid, 1)
            pair_ts = np.maximum(other.ts[oid], ts[bi])
            out.append(self._pair_seg(side, [c[bi] for c in src_cols],
                                      [c[oid] for c in other.vals],
                                      pair_ts, False))
        if self._outer_for(side):
            unmatched = counts == 0
            if unmatched.any():
                out.append(self._pad_seg(side, [c[unmatched] for c in src_cols],
                                         ts[unmatched], False))
                mine.null_emitted[new_ids[unmatched]] = True

    def _retract_row(self, side: int, k: int, t: int, vals: tuple,
                     out: list) -> None:
        mine = self.stores[side]
        other = self.stores[1 - side]
        found = None
        for gid in np.flatnonzero(
                (mine.keys[: mine.n] == k) & mine.alive[: mine.n]).tolist():
            if all(v == mine.vals[i][gid] for i, v in enumerate(vals)):
                found = gid
                break
        if found is None:
            raise RuntimeError(
                "retract for a row never seen (updating join ordering violation)"
            )
        null_emitted = bool(mine.null_emitted[found])
        mine.kill(found)
        row_vals = [_object_col([v]) for v in vals]
        if null_emitted:
            out.append(self._pad_seg(side, row_vals,
                                     np.array([t], dtype=np.int64), True))
            return
        m = other.live_ids()
        m = m[other.keys[m] == k]
        if len(m):
            other.match_count[m] -= 1
            pair_ts = np.maximum(other.ts[m], t)
            out.append(self._pair_seg(
                side, [c.repeat(len(m)) for c in row_vals],
                [c[m] for c in other.vals], pair_ts, True))
            if self._outer_for(1 - side):
                renull = m[other.match_count[m] == 0]
                if len(renull):
                    out.append(self._pad_seg(
                        1 - side, [c[renull] for c in other.vals],
                        np.maximum(other.ts[renull], t), False))
                    other.null_emitted[renull] = True

    def _pair_seg(self, side, my_vals, other_vals, ts, retract):
        lv, rv = (my_vals, other_vals) if side == 0 else (other_vals, my_vals)
        return (lv, rv, ts, retract, len(ts))

    def _pad_seg(self, side, vals, ts, retract):
        lv, rv = (vals, None) if side == 0 else (None, vals)
        return (lv, rv, ts, retract, len(ts))

    def _emit(self, segments: list, collector) -> None:
        cols: dict[str, np.ndarray] = {}
        for i, (out_name, _src) in enumerate(self.left_names):
            cols[out_name] = np.concatenate(
                [lv[i] if lv is not None else _null_col(k)
                 for lv, _rv, _t, _r, k in segments])
        for i, (out_name, _src) in enumerate(self.right_names):
            cols[out_name] = np.concatenate(
                [rv[i] if rv is not None else _null_col(k)
                 for _lv, rv, _t, _r, k in segments])
        cols[IS_RETRACT_FIELD] = np.concatenate(
            [np.full(k, r) for _lv, _rv, _t, r, k in segments])
        cols[TIMESTAMP_FIELD] = np.concatenate(
            [np.asarray(t, dtype=np.int64) for _lv, _rv, t, _r, k in segments])
        collector.collect(Batch(cols))

    # ------------------------------------------------------------------

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            return watermark
        cutoff = watermark.value - self.ttl
        oldest = None
        for store in self.stores:
            live = store.live_ids()
            if not len(live):
                continue
            expired = live[store.ts[live] < cutoff]
            if len(expired):
                self.late_rows += len(expired)
                store.kill(expired)
                live = store.live_ids()
            if len(live):
                lo = int(store.ts[live].min())
                oldest = lo if oldest is None else min(oldest, lo)
        if self._annexes is not None:
            # spilled rows age out too (zone-map gated, whole-run drops when
            # possible), and alive cold rows hold the watermark exactly like
            # resident ones; the budget check runs here — off the per-batch
            # hot path, after expiry freed whatever it could
            for annex in self._annexes:
                self.late_rows += annex.expire(cutoff)
                lo = annex.oldest_ts()
                if lo is not None:
                    oldest = lo if oldest is None else min(oldest, lo)
            self._maybe_spill()
        # future emissions carry ts = max(sides) >= the oldest buffered row;
        # hold the watermark to that bound so downstream never sees late rows
        held = watermark.value if oldest is None else min(watermark.value, oldest)
        from ..types import Watermark

        return Watermark.event_time(held)

    def handle_checkpoint(self, barrier, ctx, collector):
        if self._annexes is not None:
            from ..state.spill import checkpoint_manifest

            for a in self._annexes:
                a.epoch = barrier.epoch
            self._maybe_spill()
            # spilled runs checkpoint BY REFERENCE: the manifest (run list,
            # dead-row sets) rides the epoch; the files are never re-uploaded
            checkpoint_manifest(ctx, "left__spill", self._annexes[0])
            checkpoint_manifest(ctx, "right__spill", self._annexes[1])
        for side, name in ((0, "left"), (1, "right")):
            tbl = ctx.table_manager.expiring_time_key(name, self.ttl)
            store = self.stores[side]
            live = store.live_ids()
            if not len(live):
                tbl.replace_all([])
                continue
            srcs = [src for _o, src in self._src_names(side)]
            cols: dict[str, np.ndarray] = {
                TIMESTAMP_FIELD: store.ts[live].copy(),
                KEY_FIELD: store.keys[live].copy().view(np.uint64),
                "__match_count": store.match_count[live].copy(),
                "__null_emitted": store.null_emitted[live].copy(),
            }
            for i, s in enumerate(srcs):
                cols[s] = store.vals[i][live]
            tbl.replace_all([Batch(cols)])


class LookupJoin(Operator):
    """config: connector (object with lookup(keys)->dict, from the connector
    registry), key_exprs: [Expr] evaluated on the stream, right_names:
    [(out_name, field)] columns pulled from the looked-up row, join_type:
    inner|left, cache_ttl_micros, cache_max_size, max_concurrency.

    Async pipelined lookups (reference lookup_join.rs:35): cache misses are
    batched per input batch and dispatched to a bounded thread pool off the
    task thread; batches emit strictly in input order as their fetches land,
    and watermarks/barriers drain everything in flight first, so a slow
    lookup source overlaps N fetches instead of serializing the hot loop."""

    def __init__(self, cfg: dict):
        from collections import deque

        self.connector = cfg["connector"]
        self.key_exprs = list(cfg["key_exprs"])
        self.right_names: list[tuple[str, str]] = list(cfg["right_names"])
        self.join_type = cfg.get("join_type", "left")
        self.cache_ttl = int(cfg.get("cache_ttl_micros", 60_000_000))
        self.cache_max = int(cfg.get("cache_max_size", 100_000))
        self.max_concurrency = int(cfg.get("max_concurrency", 16))
        # key -> (row|None, wall_micros); checkpointed into table "c" and
        # restored, so a replayed batch that still hits the cache resolves
        # to the value the original run emitted. The TTL stays WALL-clock:
        # entries whose TTL elapsed during recovery downtime re-fetch (and
        # may see fresher external rows) — a lookup join is only as
        # replay-stable as its cache is fresh, by design
        self.cache: dict = {}
        self._pool = None
        # FIFO of ("batch", batch, keys, resolved, missing, fut, borrowed)
        # and ("wm", Watermark) markers: strictly ordered emission
        self._pending = deque()  # state: ephemeral — drained (block=True) at every barrier before the snapshot
        # key -> in-flight Future: concurrent batches borrow a pending
        # fetch instead of re-asking the source for the same key
        self._inflight: dict = {}  # state: ephemeral — emptied by the blocking barrier drain; every future resolves with its batch

    def tables(self):
        return [TableSpec("c", "global_keyed")]

    def on_start(self, ctx):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=self.max_concurrency, thread_name_prefix="lookup-join")
        saved = ctx.table_manager.global_keyed("c").get(
            ctx.task_info.subtask_index)
        if saved and not self.cache:
            # `not self.cache` guards the lazy on_start re-call in
            # process_batch from clobbering the live cache mid-run
            self.cache = {k: tuple(v) for k, v in saved}

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        key_cols = [
            np.asarray(eval_expr(e, batch.columns, n)) for e in self.key_exprs
        ]
        keys = [
            tuple(c[i] for c in key_cols) if len(key_cols) > 1 else key_cols[0][i]
            for i in range(n)
        ]
        now = int(_time.time() * 1e6)  # lint: waive LR109 — lookup-cache TTL wall clock, not self-measurement
        # resolve hits AT SUBMIT TIME: deferred emission must not depend on
        # cache entries that a later eviction sweep could remove
        resolved: dict = {}
        missing: list = []
        borrowed: dict = {}
        # lint: waive LR204 — populates lookup maps only; emitted rows are ordered by the batch's own key list, and the missing-list order is an external-call detail
        for k in set(keys):
            ent = self.cache.get(k)
            if ent is not None and now - ent[1] <= self.cache_ttl:
                resolved[k] = ent[0]
            elif k in self._inflight:
                borrowed[k] = self._inflight[k]
            else:
                missing.append(k)
        fut = None
        if missing:
            if self._pool is None:
                self.on_start(ctx)
            fut = self._pool.submit(self.connector.lookup, missing)
            for k in missing:
                self._inflight[k] = fut
        self._pending.append(("batch", batch, keys, resolved, missing, fut, borrowed))
        self._drain(collector, block=False)
        # backpressure: bound in-flight batches so a stalled source cannot
        # queue unbounded memory behind the pool
        while sum(1 for e in self._pending if e[0] == "batch") > 2 * self.max_concurrency:
            self._emit_head(collector)

    def _head_ready(self) -> bool:
        e = self._pending[0]
        if e[0] == "wm":
            return True
        fut, borrowed = e[5], e[6]
        if fut is not None and not fut.done():
            return False
        return all(f.done() for f in borrowed.values())

    def _drain(self, collector, block: bool) -> None:
        while self._pending:
            if not block and not self._head_ready():
                return
            self._emit_head(collector)

    def _emit_head(self, collector) -> None:
        entry = self._pending.popleft()
        if entry[0] == "wm":
            from ..types import Signal

            collector.broadcast(Signal.watermark_of(entry[1]))
            return
        _tag, batch, keys, resolved, missing, fut, borrowed = entry
        now = int(_time.time() * 1e6)  # lint: waive LR109 — lookup-cache TTL wall clock, not self-measurement
        val_of = dict(resolved)
        if fut is not None:
            fetched = fut.result()
            for k in missing:
                val_of[k] = fetched.get(k)
                self.cache[k] = (fetched.get(k), now)
                if self._inflight.get(k) is fut:
                    del self._inflight[k]
        # lint: waive LR204 — fills the val_of lookup map; row order comes from the batch's key list below
        for k, bf in borrowed.items():
            val_of[k] = bf.result().get(k)
        rows = [val_of[k] for k in keys]
        if len(self.cache) > self.cache_max:
            # evict oldest entries — after gathering, so this batch's keys
            # cannot be evicted before they are read
            # key-repr tie-break: same-wall entries must evict identically
            # on replay (dict order diverges after a restore)
            by_age = sorted(self.cache.items(),
                            key=lambda kv: (kv[1][1], str(kv[0])))
            for k, _ in by_age[: len(self.cache) - self.cache_max]:
                del self.cache[k]
        n = batch.num_rows
        present = np.array([r is not None for r in rows], dtype=bool)
        if self.join_type == "inner" and not present.all():
            batch = batch.filter(present)
            rows = [r for r, p in zip(rows, present) if p]
            present = present[present]
            n = batch.num_rows
            if n == 0:
                return
        cols = dict(batch.columns)
        for out_name, field in self.right_names:
            vals = [r.get(field) if r is not None else None for r in rows]
            sample = next((v for v in vals if v is not None), None)
            if isinstance(sample, (str, type(None))) or not present.all():
                cols[out_name] = _object_col(vals)
            else:
                cols[out_name] = np.array(vals)
        collector.collect(Batch(cols))

    def handle_watermark(self, watermark, ctx, collector):
        # watermark-held ordered emission WITHOUT stalling the pipeline:
        # the watermark queues behind its preceding batches and broadcasts
        # as the queue drains (same shape as TumblingAggregate's pending
        # queue) — blocking here would cap lookup overlap at one batch,
        # since upstream emits a watermark after nearly every batch
        self._drain(collector, block=False)
        if not self._pending:
            return watermark
        self._pending.append(("wm", watermark))
        return None

    def handle_checkpoint(self, barrier, ctx, collector):
        self._drain(collector, block=True)
        # snapshot the cache (sorted by key repr: deterministic file bytes);
        # nothing is in flight after the blocking drain
        ctx.table_manager.global_keyed("c").insert(
            ctx.task_info.subtask_index,
            sorted(self.cache.items(), key=lambda kv: str(kv[0])))

    def on_close(self, ctx, collector):
        self._drain(collector, block=True)
        if self._pool is not None:
            self._pool.shutdown(wait=False)


@register_operator(OpName.INSTANT_JOIN)
def _make_instant(cfg: dict):
    return InstantJoin(cfg)


@register_operator(OpName.JOIN_WITH_EXPIRATION)
def _make_expiring(cfg: dict):
    return JoinWithExpiration(cfg)


@register_operator(OpName.LOOKUP_JOIN)
def _make_lookup(cfg: dict):
    return LookupJoin(cfg)
