"""Stateless / lightly-stateful built-in operators.

- ValueOperator: projection + filter (reference ArrowValue,
  crates/arroyo-worker/src/arrow/mod.rs:48-163) evaluated with the expression
  engine instead of a DataFusion plan.
- KeyOperator: key-column calculation + routing hash (reference ArrowKey,
  arrow/mod.rs:165-228); downstream edge is Shuffle.
- WatermarkGenerator: expression watermark w/ idle detection (reference
  arrow/watermark_generator.rs:33).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..engine.engine import register_operator
from ..expr import Expr, eval_expr
from ..graph import OpName
from ..hashing import hash_columns
from ..operators.base import Operator, OperatorContext, TableSpec
from ..operators.collector import Collector
from ..types import Watermark


class ValueOperator(Operator):
    """config: projections: list[(name, Expr)] | None (passthrough),
    filter: Expr | None. _timestamp passes through unless projected."""

    def __init__(self, cfg: dict):
        self.projections: Optional[list[tuple[str, Expr]]] = cfg.get("projections")
        self.filter: Optional[Expr] = cfg.get("filter")
        # with projections, the filter only needs to materialize the columns
        # the projections (and the internal passthroughs below) read — not
        # every source column (hot-path copy cut; q8 branch batches carry
        # 2x the columns their projections touch)
        self._needed: Optional[set] = None
        if self.projections is not None:
            needed = {TIMESTAMP_FIELD, KEY_FIELD, "_is_retract"}
            for _name, e in self.projections:
                needed |= e.columns()
            self._needed = needed

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        if self.filter is not None:
            mask = np.asarray(eval_expr(self.filter, batch.columns, n), dtype=bool)
            if not mask.any():
                return
            if not mask.all():
                if self._needed is not None:
                    batch = Batch({k: v[mask] for k, v in batch.columns.items()
                                   if k in self._needed})
                else:
                    batch = batch.filter(mask)
            n = batch.num_rows
        if self.projections is None:
            collector.collect(batch)
            return
        cols: dict[str, np.ndarray] = {}
        for name, expr in self.projections:
            cols[name] = eval_expr(expr, batch.columns, n)
        if TIMESTAMP_FIELD not in cols:
            cols[TIMESTAMP_FIELD] = batch.timestamps
        if KEY_FIELD in batch.columns and KEY_FIELD not in cols:
            cols[KEY_FIELD] = batch.keys
        # updating streams: the retract flag rides along through projections
        if "_is_retract" in batch.columns and "_is_retract" not in cols:
            cols["_is_retract"] = batch.columns["_is_retract"]
        collector.collect(Batch(cols))


class UnnestOperator(Operator):
    """config: column (list-valued), out_name, out_dtype. Explodes each
    row's list into one output row per element; all other columns repeat.
    Rows with empty lists vanish (reference UnnestRewriter semantics,
    rewriters.rs:323 / datafusion unnest)."""

    def __init__(self, cfg: dict):
        self.column = str(cfg["column"])
        self.out_name = str(cfg.get("out_name", self.column))
        self.out_dtype = cfg.get("out_dtype")

    def process_batch(self, batch, ctx, collector, input_index=0):
        import itertools

        col = batch.columns[self.column]
        # UNNEST of a NULL array produces zero rows for that input row
        lens = np.fromiter((0 if v is None else len(v) for v in col),
                           dtype=np.int64, count=batch.num_rows)
        total = int(lens.sum())
        if total == 0:
            return
        flat = list(itertools.chain.from_iterable(v for v in col if v is not None))
        cols: dict[str, np.ndarray] = {}
        for name, c in batch.columns.items():
            if name == self.column:
                continue
            cols[name] = np.repeat(np.asarray(c), lens)
        if self.out_dtype and self.out_dtype != "string":
            from ..batch import Field

            vals = np.array(flat, dtype=Field("_", self.out_dtype).numpy_dtype())
        else:
            from ..batch import object_column

            vals = object_column(flat)
        cols[self.out_name] = vals
        collector.collect(Batch(cols))


class KeyOperator(Operator):
    """config: keys: list[(name, Expr)] — computes group-by columns and the
    uint64 routing hash (_key)."""

    def __init__(self, cfg: dict):
        self.keys: list[tuple[str, Expr]] = cfg["keys"]

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        cols = dict(batch.columns)
        key_cols = []
        for name, expr in self.keys:
            col = eval_expr(expr, batch.columns, n)
            cols[name] = col
            key_cols.append(np.asarray(col))
        cols[KEY_FIELD] = hash_columns(key_cols)
        collector.collect(Batch(cols))


class WatermarkGenerator(Operator):
    """config: expr: Expr (watermark value per row, e.g. _timestamp - 5s),
    interval_micros: min event-time advance between emissions (default: emit
    whenever it advances), idle_time_micros: wall-time idleness before
    emitting Watermark::Idle (reference watermark_generator.rs:28-60)."""

    def __init__(self, cfg: dict):
        self.expr: Expr = cfg["expr"]
        self.interval_micros: int = cfg.get("interval_micros", 0)
        self.idle_time_micros: Optional[int] = cfg.get("idle_time_micros")
        # optional shared list: (watermark_value, wall_monotonic) appended at
        # each emission — the injection half of the watermark-to-emit
        # latency metric (BASELINE.md; the sink records the arrival half)
        self.latency_log: Optional[list] = cfg.get("latency_log")  # state: ephemeral — bench-only latency probe list; never read into emitted data
        self.max_watermark: Optional[int] = None
        self.last_emitted: Optional[int] = None
        # state: ephemeral — wall-clock idle detection; a restored task re-derives idleness from real time, and idle watermarks carry no data
        self.last_event_wall: float = time.monotonic()  # lint: waive LR109 — event-time idle detection needs a wall clock, not self-measurement
        self.idle_sent = False  # state: ephemeral — idle latch re-derived from the wall clock after restore; idle watermarks carry no data

    def tables(self):
        return [TableSpec("s", "global_keyed")]

    def on_start(self, ctx):
        tbl = ctx.table_manager.global_keyed("s")
        st = tbl.get(ctx.task_info.subtask_index)
        if st is not None:
            self.max_watermark = st.get("max_watermark")
            self.last_emitted = st.get("last_emitted")

    def tick_interval_micros(self):
        return self.idle_time_micros

    def handle_tick(self, ctx, collector):
        if self.idle_time_micros is None or self.idle_sent:
            return
        if (time.monotonic() - self.last_event_wall) * 1e6 >= self.idle_time_micros:  # lint: waive LR109 — idle-watermark timeout is wall-clock by definition
            from ..types import Signal

            collector.broadcast(Signal.watermark_of(Watermark.idle()))
            self.idle_sent = True

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        vals = np.asarray(eval_expr(self.expr, batch.columns, n))
        m = int(vals.max())
        collector.collect(batch)
        self.observe_batch_max(m, collector)

    def observe_batch_max(self, m: int, collector) -> None:
        """Watermark state machine over one batch's max event-time value —
        shared by the interpreted hook above and the compiled segment's
        host finisher (engine/segment.py), so the two paths cannot drift.
        Called AFTER the batch's rows are collected: the emitted watermark
        must never overtake the data it covers."""
        self.last_event_wall = time.monotonic()  # lint: waive LR109 — idle-detection clock, not self-measurement
        self.idle_sent = False
        if self.max_watermark is None or m > self.max_watermark:
            self.max_watermark = m
            if self.last_emitted is None or m - self.last_emitted >= self.interval_micros:
                self.last_emitted = m
                from ..types import Signal

                if self.latency_log is not None:
                    self.latency_log.append((m, time.monotonic()))  # lint: waive LR109 — bench latency probe stamps injection wall time by design
                collector.broadcast(Signal.watermark_of(Watermark.event_time(m)))

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.global_keyed("s").insert(
            ctx.task_info.subtask_index,
            {"max_watermark": self.max_watermark, "last_emitted": self.last_emitted},
        )

    def handle_watermark(self, watermark, ctx, collector):
        # source-generated watermarks (rare) pass through; ours are broadcast
        # from process_batch
        return None


@register_operator(OpName.VALUE)
def _make_value(cfg: dict):
    return ValueOperator(cfg)


@register_operator(OpName.KEY)
def _make_key(cfg: dict):
    return KeyOperator(cfg)


@register_operator(OpName.UNNEST)
def _make_unnest(cfg: dict):
    return UnnestOperator(cfg)


@register_operator(OpName.WATERMARK)
def _make_watermark(cfg: dict):
    return WatermarkGenerator(cfg)
