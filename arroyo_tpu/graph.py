"""Logical dataflow IR.

Equivalent of the reference's LogicalProgram
(crates/arroyo-datastream/src/logical.rs:299 — petgraph DiGraph<LogicalNode,
LogicalEdge>, OperatorName :28-43, LogicalEdgeType :46-51) with JSON (not
protobuf) serialization. Node configs are plain dicts; the SQL planner fills
them and the worker engine's construct_operator maps op_name -> operator class
(reference engine.rs:867-879).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .batch import Schema


class OpName(enum.Enum):
    """Mirrors reference OperatorName (logical.rs:28-43)."""

    SOURCE = "source"
    SINK = "sink"
    VALUE = "value"  # projection/filter (ArrowValue)
    KEY = "key"  # key calculation (ArrowKey)
    WATERMARK = "watermark"  # ExpressionWatermark
    TUMBLING_AGGREGATE = "tumbling_aggregate"
    SLIDING_AGGREGATE = "sliding_aggregate"
    SESSION_AGGREGATE = "session_aggregate"
    UPDATING_AGGREGATE = "updating_aggregate"
    JOIN_WITH_EXPIRATION = "join_with_expiration"  # updating join
    INSTANT_JOIN = "instant_join"  # windowed join
    LOOKUP_JOIN = "lookup_join"
    WINDOW_FUNCTION = "window_function"  # SQL OVER
    ASYNC_UDF = "async_udf"
    UNNEST = "unnest"  # array explode (reference UnnestRewriter, rewriters.rs:323)
    CHAINED = "chained"  # fused run of operators (optimizers.rs:40 analog)


class EdgeType(enum.Enum):
    """Mirrors reference LogicalEdgeType (logical.rs:46-51)."""

    FORWARD = "forward"
    SHUFFLE = "shuffle"
    LEFT_JOIN = "left_join"
    RIGHT_JOIN = "right_join"


@dataclass
class Node:
    node_id: str
    op: OpName
    config: dict
    parallelism: int = 1
    description: str = ""


@dataclass
class Edge:
    src: str
    dst: str
    edge_type: EdgeType
    schema: Schema


class Graph:
    """Small DAG container (adjacency-list petgraph stand-in)."""

    def __init__(self):
        self.nodes: dict[str, Node] = {}
        self.edges: list[Edge] = []

    def add_node(self, node: Node) -> Node:
        if node.node_id in self.nodes:
            raise ValueError(f"duplicate node {node.node_id}")
        self.nodes[node.node_id] = node
        return node

    def add_edge(self, src: str, dst: str, edge_type: EdgeType, schema: Schema) -> Edge:
        for nid in (src, dst):
            if nid not in self.nodes:
                raise ValueError(f"unknown node {nid}")
        e = Edge(src, dst, edge_type, schema)
        self.edges.append(e)
        return e

    def in_edges(self, node_id: str) -> list[Edge]:
        return [e for e in self.edges if e.dst == node_id]

    def out_edges(self, node_id: str) -> list[Edge]:
        return [e for e in self.edges if e.src == node_id]

    def sources(self) -> list[Node]:
        return [n for n in self.nodes.values() if not self.in_edges(n.node_id)]

    def sinks(self) -> list[Node]:
        return [n for n in self.nodes.values() if not self.out_edges(n.node_id)]

    def topo_order(self) -> list[Node]:
        indeg = {nid: len(self.in_edges(nid)) for nid in self.nodes}
        ready = sorted([nid for nid, d in indeg.items() if d == 0])
        out: list[Node] = []
        while ready:
            nid = ready.pop(0)
            out.append(self.nodes[nid])
            for e in self.out_edges(nid):
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    ready.append(e.dst)
        if len(out) != len(self.nodes):
            raise ValueError("graph has a cycle")
        return out

    # -- serialization ------------------------------------------------------

    def to_json(self) -> dict:
        return {
            "nodes": [
                {
                    "node_id": n.node_id,
                    "op": n.op.value,
                    "config": _jsonable(n.config),
                    "parallelism": n.parallelism,
                    "description": n.description,
                }
                for n in self.nodes.values()
            ],
            "edges": [
                {
                    "src": e.src,
                    "dst": e.dst,
                    "edge_type": e.edge_type.value,
                    "schema": e.schema.to_json(),
                }
                for e in self.edges
            ],
        }

    @staticmethod
    def from_json(d: dict) -> "Graph":
        g = Graph()
        for nd in d["nodes"]:
            g.add_node(
                Node(nd["node_id"], OpName(nd["op"]), _config_from_json(nd["config"]),
                     nd["parallelism"], nd.get("description", ""))
            )
        for ed in d["edges"]:
            g.add_edge(ed["src"], ed["dst"], EdgeType(ed["edge_type"]), Schema.from_json(ed["schema"]))
        return g

    def dumps(self) -> str:
        return json.dumps(self.to_json())

    @staticmethod
    def loads(s: str) -> "Graph":
        return Graph.from_json(json.loads(s))

    def dot(self) -> str:
        """Graphviz rendering (stands in for `arroyo visualize`, main.rs:492)."""
        lines = ["digraph pipeline {"]
        for n in self.nodes.values():
            lines.append(f'  "{n.node_id}" [label="{n.op.value}\\np={n.parallelism}\\n{n.description}"];')
        for e in self.edges:
            lines.append(f'  "{e.src}" -> "{e.dst}" [label="{e.edge_type.value}"];')
        lines.append("}")
        return "\n".join(lines)


def _jsonable(obj):
    """Conversion of node configs to JSON-safe values with full round-trip
    for the planner-produced surface: expression ASTs serialize as tagged
    trees (expr.expr_to_json — the reference's protobuf-plan analog,
    api.proto:30-110), schemas as tagged dicts. Callables (e.g. the
    in-process input_dtype_of convenience) are dropped — the planner also
    records the declarative "input_dtypes" map operators rebuild it from.
    Anything else degrades to a repr string for display-only graphs."""
    from .expr import Expr, expr_to_json

    if isinstance(obj, dict):
        # input_dtype_of is rebuildable from the serialized "input_dtypes"
        # map; any OTHER callable marks the graph unshippable so the
        # round-trip check fails loudly and the control plane ships SQL
        return {
            k: ({"__callable__": repr(v)} if callable(v) else _jsonable(v))
            for k, v in obj.items()
            if not (k == "input_dtype_of" and callable(v))
        }
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, Expr):
        return expr_to_json(obj)
    if isinstance(obj, Schema):
        return {"__schema__": obj.to_json()}
    return repr(obj)


def _config_from_json(obj):
    from .expr import expr_from_json

    if isinstance(obj, dict):
        if "__e__" in obj:
            return expr_from_json(obj)
        if "__schema__" in obj:
            return Schema.from_json(obj["__schema__"])
        if "__callable__" in obj:
            raise ValueError(
                f"graph config holds a live callable and cannot ship as IR: "
                f"{obj['__callable__']}"
            )
        return {k: _config_from_json(v) for k, v in obj.items()}
    if isinstance(obj, list):
        # planner configs carry pair-lists ((name, expr), ...); tuples and
        # lists are interchangeable for every consumer
        return [_config_from_json(v) for v in obj]
    return obj
