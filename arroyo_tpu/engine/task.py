"""Physical subtask run loop.

Equivalent of the reference's operator_run_behavior
(crates/arroyo-operator/src/operator.rs:863-996): a select-loop over control
messages, the fused input stream, and a tick interval; handles
SignalMessage::{Barrier, Watermark, Stop, EndOfData} (:624-676); aligned
barriers block inputs that already delivered the current epoch's barrier
(:966-975, CheckpointCounter lib.rs:71); watermark merge is the min over
per-input watermarks with Idle short-circuit (context.rs:33-84
WatermarkHolder).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
import traceback
from collections import deque
from typing import Optional, Union

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..faults import fault_point
from ..operators.base import Operator, OperatorContext, SourceOperator
from ..operators.collector import Collector
from ..types import (
    CheckpointBarrier,
    CheckpointEvent,
    ControlMessage,
    ControlResp,
    Signal,
    SignalKind,
    SourceFinishType,
    TaskInfo,
    Watermark,
)
from .queues import TaskInbox


class WatermarkHolder:
    """Min-merge of per-input watermarks (reference context.rs:33-84)."""

    def __init__(self, n_inputs: int):
        self._wms: dict[int, Optional[Watermark]] = {i: None for i in range(n_inputs)}

    def set(self, input_index: int, wm: Watermark) -> None:
        if input_index in self._wms:
            self._wms[input_index] = wm

    def remove(self, input_index: int) -> None:
        self._wms.pop(input_index, None)

    def merged(self) -> Optional[Watermark]:
        """None until every live input has reported; Idle only if all idle."""
        if not self._wms:
            return None
        values = list(self._wms.values())
        if any(v is None for v in values):
            return None
        non_idle = [v.value for v in values if not v.is_idle]
        if not non_idle:
            return Watermark.idle()
        return Watermark.event_time(min(non_idle))


class SourceContext:
    """What a SourceOperator.run sees: control polling + checkpoint helper
    (reference SourceContext / start_checkpoint, operator.rs:313-341)."""

    def __init__(self, task: "Task"):
        self._task = task
        self.ctx = task.ctx

    def poll_control(self) -> Optional[ControlMessage]:
        # connector run loops poll between batches, so this doubles as the
        # source-task liveness beat (Engine.heartbeat) AND the time-based
        # coalescing flush point for source emissions
        self._task.last_progress = time.monotonic()
        self._task.collector.flush_expired(self._task.last_progress)
        if self._task.profiler is not None:
            # incremental self-time: live snapshots must show a streaming
            # source's busy%, not wait for run() to return
            self._task.profiler.source_tick()
            self._task.profiler.refresh()
        try:
            return self._task.control_queue.get_nowait()
        except _queue.Empty:
            return None

    def start_checkpoint(self, barrier: CheckpointBarrier) -> None:
        self._task.run_source_checkpoint(barrier)


class Task:
    def __init__(
        self,
        task_info: TaskInfo,
        operator: Union[Operator, SourceOperator],
        inbox: Optional[TaskInbox],
        collector: Collector,
        ctx: OperatorContext,
        resp_queue: "_queue.Queue[ControlResp]",
        n_inputs: int = 0,
    ):
        self.task_info = task_info
        self.operator = operator
        self.inbox = inbox
        self.collector = collector
        self.ctx = ctx
        self.resp_queue = resp_queue
        self.n_inputs = n_inputs
        self.control_queue: "_queue.Queue[ControlMessage]" = _queue.Queue()
        self.thread: Optional[threading.Thread] = None
        self.is_source = isinstance(operator, SourceOperator)
        # liveness beat: updated every run-loop iteration / control poll /
        # backpressure wait; a hung task stops beating (Engine.heartbeat)
        self.last_progress = time.monotonic()  # concurrency: single-writer — monotonic heartbeat timestamp owned by the task thread; watchdog reads are GIL-atomic float snapshots and only ever see a slightly stale beat
        # epoch being snapshotted right now (None otherwise): an exception
        # mid-checkpoint stamps its OPERATOR_PANIC event with the epoch
        self._ckpt_epoch: Optional[int] = None
        # True when the run loop drained cleanly (graceful EOF or
        # checkpoint-then-stop): only such finishes carry final/durable
        # state and may stand in for epoch coverage (ControlResp.clean)
        self.finished_clean = True
        from ..metrics import registry as _metrics_registry

        self.metrics = _metrics_registry.task(
            task_info.job_id, task_info.node_id, task_info.subtask_index
        )
        # cost attribution (obs/profile.py): self-time wrapping for every
        # operator hook, state-size gauges, and the key-skew sketch. None
        # when profile.enabled is off — the run loop then does zero extra
        # work. Built AFTER the table-manager restore (Engine.build runs
        # restore before constructing the Task) so the sketch resumes the
        # exact summary the checkpoint persisted.
        from ..obs.profile import make_profiler

        self.profiler = make_profiler(self.metrics, task_info,
                                      ctx.table_manager, operator)
        # one key space per sketch: an operator that keyed-shuffles its
        # OUTPUT is observed at the collector's shuffle boundary (the new
        # routing keys — what a re-keying operator is about to melt a
        # downstream subtask with); only operators that do NOT shuffle
        # observe their keyed INPUT (window/join insert paths). Feeding
        # both would mix two hash spaces and double-count pass-throughs.
        from ..graph import EdgeType as _EdgeType

        self.observe_input_keys = not any(
            len(e.dests) > 1 and e.edge_type != _EdgeType.FORWARD
            for e in collector.out_edges)
        if inbox is not None:
            self.metrics.queue_size = inbox.row_budget * inbox.n_inputs
            # an idle queue is an EMPTY queue, not a full one
            self.metrics.queue_rem = self.metrics.queue_size
            inbox.metrics = self.metrics  # consumer-side transit histogram
        collector.metrics = self.metrics
        # terminal operators (sinks) observe end-to-end event latency
        self._terminal = not collector.out_edges

    def _observe_sink_latency(self, batch: Batch) -> None:
        """Sink-side end-to-end latency: wall clock at arrival minus the
        batch's newest event timestamp (seconds)."""
        if TIMESTAMP_FIELD not in batch:
            return
        ts_max = batch[TIMESTAMP_FIELD].max()
        self.metrics.sink_event_latency.observe(
            max(0.0, time.time() - float(ts_max) / 1e6))

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        name = f"{self.task_info.node_id}-{self.task_info.subtask_index}"
        self.thread = threading.Thread(target=self._run_guarded, name=name, daemon=True)
        self.thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self.thread:
            self.thread.join(timeout)

    def _resp(self, kind: str, **kw) -> None:
        self.resp_queue.put(
            ControlResp(kind=kind, node_id=self.task_info.node_id,
                        subtask_index=self.task_info.subtask_index, **kw)
        )

    # ------------------------------------------------------------- run loops

    def _beat(self) -> None:
        self.last_progress = time.monotonic()

    def _run_guarded(self) -> None:
        try:
            # a producer blocked on a full inbox is backpressured, not hung:
            # the inbox's budget wait loop beats through this thread hook
            threading.current_thread().arroyo_beat = self._beat  # type: ignore[attr-defined]
            self._resp("task_started")
            if self.is_source:
                self._run_source()
            else:
                self._run_operator()
            self._resp("task_finished", clean=self.finished_clean)
        except Exception:
            tb = traceback.format_exc()
            # structured event BEFORE the failure propagates: the job event
            # feed names the operator/subtask (+ epoch when the panic hit
            # mid-checkpoint) with a stable traceback digest, so a crashed
            # pipeline is diagnosable from `logs` without stderr archaeology
            from ..obs.events import recorder as _events
            from ..obs.events import traceback_digest

            dig = traceback_digest(tb)
            _events.record(
                self.task_info.job_id, "ERROR", "OPERATOR_PANIC",
                message=dig["error"] or "operator raised",
                node=self.task_info.node_id,
                subtask=self.task_info.subtask_index,
                epoch=self._ckpt_epoch,
                data={"digest": dig["digest"],
                      "operator": self.task_info.operator_name},
            )
            self._resp("task_failed", error=tb)

    def _run_source(self) -> None:
        op: SourceOperator = self.operator  # type: ignore[assignment]
        prof = self.profiler
        op.on_start(self.ctx)
        sctx = SourceContext(self)
        if prof is None:
            finish = op.run(sctx, self.collector)
            op.on_close(self.ctx, self.collector)
        else:
            # thread-CPU accumulates incrementally via source_tick (the
            # connector poll path) so LIVE snapshots carry the source's
            # busy%; this first tick just stamps the mark, the final one
            # catches the tail after run() returns
            prof.source_tick()
            finish = op.run(sctx, self.collector)
            prof.source_tick()
            t0 = prof.begin()
            op.on_close(self.ctx, self.collector)
            prof.end("close", t0)
            prof.refresh(force=True)
        if finish == SourceFinishType.GRACEFUL:
            # persist the drained offset so a restore from ANY later epoch
            # does not replay this source (state is constant after EOF and
            # all emitted data precedes downstream epoch barriers)
            if prof is not None:
                prof.checkpoint_sketch()
            self.ctx.table_manager.checkpoint("final", self.ctx.watermark())
            self.collector.broadcast(Signal.end_of_data())
        elif finish == SourceFinishType.IMMEDIATE:
            # stopped/aborted: no final snapshot exists, so this exit must
            # NOT count as epoch coverage (a restore would replay from zero)
            self.finished_clean = False
            self.collector.broadcast(Signal.stop())
        # FINAL: checkpoint-then-stop already broadcast the barrier; end data.
        if finish == SourceFinishType.FINAL:
            self.collector.broadcast(Signal.end_of_data())

    def run_source_checkpoint(self, barrier: CheckpointBarrier) -> None:
        """Checkpoint table state then broadcast the barrier downstream
        (reference operator.rs:313-341)."""
        self._resp("checkpoint_event", checkpoint_event=CheckpointEvent(
            barrier.epoch, self.task_info.node_id, self.task_info.subtask_index,
            int(time.time() * 1e6), "started_checkpointing"))
        self._ckpt_epoch = barrier.epoch
        prof = self.profiler
        t0 = prof.begin() if prof is not None else None
        if prof is not None:
            prof.checkpoint_sketch()
        meta = self.ctx.table_manager.checkpoint(barrier.epoch, self.ctx.watermark())
        if prof is not None:
            prof.end("checkpoint", t0)
            # the snapshot CPU is attributed above; the source's rolling
            # process clock must not count it again
            prof.source_reset()
            prof.refresh(force=True)
        # chaos hook: a crash HERE is the worst case — state files for this
        # epoch are on disk but the epoch never completes (no job metadata),
        # so recovery must ignore them and restore the previous epoch
        fault_point("worker", barrier=barrier.epoch,
                    node=self.task_info.node_id,
                    subtask=self.task_info.subtask_index)
        self.collector.broadcast(Signal.barrier_of(barrier))
        self._ckpt_epoch = None
        self._resp("checkpoint_completed", epoch=barrier.epoch, subtask_metadata=meta)

    def _run_operator(self) -> None:
        op: Operator = self.operator  # type: ignore[assignment]
        prof = self.profiler
        op.on_start(self.ctx)
        # whole-segment compilation (engine/segment.py): a chained run
        # marked compilable at plan time processes batches through ONE
        # jitted call instead of the per-member hook loop; the runner owns
        # compile/verify/fallback and delegates to op.process_batch when
        # the segment is (or becomes) interpreted. On a mesh-marked
        # segment over a sharded aggregate the runner goes one further:
        # the traced prefix AND the keyed exchange/merge run as one
        # shard_map'd jitted program per micro-batch, so the device never
        # round-trips rows to the host between segment and aggregate.
        # Signals below always take the interpreted hooks — a checkpoint
        # barrier snapshots through the operator, which reads back
        # canonical (placement-independent) state, keeping mesh-fused
        # and host-path checkpoints byte-identical.
        from .segment import runner_for

        runner = runner_for(op, self.ctx, self.metrics)
        process = op.process_batch if runner is None else runner.process_batch
        holder = WatermarkHolder(self.n_inputs)
        finished: set[int] = set()
        blocked: set[int] = set()
        held: dict[int, deque] = {}
        barrier_inputs: set[int] = set()
        current_barrier: Optional[CheckpointBarrier] = None
        pending: deque[tuple[int, Union[Batch, Signal]]] = deque()
        last_merged: Optional[Watermark] = None
        stopping = False
        stop_epoch: Optional[int] = None

        tick_us = op.tick_interval_micros()
        tick_s = tick_us / 1e6 if tick_us else None
        last_tick = time.monotonic()

        def merged_watermark_changed():
            nonlocal last_merged
            merged = holder.merged()
            if merged is not None and merged != last_merged:
                last_merged = merged
                self.ctx.last_watermark = merged
                if not merged.is_idle:
                    # watermark-lag gauge: lag (processing time minus this
                    # value) is derived at metrics-export time
                    self.metrics.watermark_micros = merged.value
                # watermark handling (window closes) is data-path work
                # driven by the stream: it attributes to "process"
                t0 = prof.begin() if prof is not None else None
                out = op.handle_watermark(merged, self.ctx, self.collector)
                if prof is not None:
                    prof.end("process", t0)
                if out is not None:
                    self.collector.broadcast(Signal.watermark_of(out))

        def run_checkpoint(b: CheckpointBarrier):
            self._resp("checkpoint_event", checkpoint_event=CheckpointEvent(
                b.epoch, self.task_info.node_id, self.task_info.subtask_index,
                int(time.time() * 1e6), "started_checkpointing"))
            self._ckpt_epoch = b.epoch
            t0 = prof.begin() if prof is not None else None
            op.handle_checkpoint(b, self.ctx, self.collector)
            if prof is not None:
                prof.checkpoint_sketch()
            meta = self.ctx.table_manager.checkpoint(b.epoch, self.ctx.watermark())
            if prof is not None:
                prof.end("checkpoint", t0)
                # barrier time is when host tables mirror device state:
                # the freshest moment for the state-size gauges
                prof.refresh(force=True)
            # chaos hook: mirror of run_source_checkpoint — crash with this
            # subtask's epoch state written but the epoch incomplete
            fault_point("worker", barrier=b.epoch,
                        node=self.task_info.node_id,
                        subtask=self.task_info.subtask_index)
            self.collector.broadcast(Signal.barrier_of(b))
            self._ckpt_epoch = None
            self._resp("checkpoint_completed", epoch=b.epoch, subtask_metadata=meta)

        def try_complete_alignment():
            """If every live input delivered the barrier, checkpoint and
            unblock held inputs; honors checkpoint-then-stop."""
            nonlocal current_barrier, stopping, stop_epoch
            if current_barrier is None:
                return
            live = set(range(self.n_inputs)) - finished
            if live <= barrier_inputs:
                run_checkpoint(current_barrier)
                if current_barrier.then_stop:
                    stopping = True
                    stop_epoch = current_barrier.epoch
                current_barrier = None
                barrier_inputs.clear()
                blocked.clear()
                # drain held items back through the loop, preserving
                # per-input order (budget released as they process)
                for i in sorted(held):
                    pending.extend(held[i])
                held.clear()

        def drain_control():
            """Out-of-band engine->task messages; commits arrive here after
            the epoch's job-level metadata is durable (reference
            ControlMessage::Commit via WorkerGrpc, operator.rs:1157)."""
            while True:
                try:
                    msg = self.control_queue.get_nowait()
                except _queue.Empty:
                    return
                if msg.kind == "commit" and msg.epoch is not None:
                    op.handle_commit(msg.epoch, self.ctx)

        while True:
            self.last_progress = time.monotonic()
            drain_control()
            # time-based coalescing flush: between items, pending sub-
            # threshold rows older than max-delay-ms go out
            self.collector.flush_expired(self.last_progress)
            if pending:
                idx, item = pending.popleft()
            else:
                timeout = 0.5
                if tick_s is not None:
                    timeout = min(timeout, max(tick_s - (time.monotonic() - last_tick), 0.0))
                deadline_f = self.collector.flush_deadline()
                if deadline_f is not None:
                    # wake exactly at the pending rows' delay deadline —
                    # waiting a full max-delay from NOW would stretch the
                    # worst-case hold to ~2x the knob
                    timeout = min(timeout, max(deadline_f - time.monotonic(), 0.0))
                got = self.inbox.get(timeout=timeout) if self.inbox else None
                if got is None:
                    if self.inbox is not None and self.inbox.closed:
                        self.finished_clean = False
                        return  # engine aborted the pipeline
                    if tick_s is not None and time.monotonic() - last_tick >= tick_s:
                        t0 = prof.begin() if prof is not None else None
                        op.handle_tick(self.ctx, self.collector)
                        if prof is not None:
                            prof.end("tick", t0)
                        last_tick = time.monotonic()
                    if prof is not None:
                        # idle wait: the throttled state-gauge/late-row sweep
                        prof.refresh()
                    if self.n_inputs == 0 or len(finished) == self.n_inputs:
                        break
                    continue
                idx, item = got
            if idx in blocked:
                held.setdefault(idx, deque()).append((idx, item))
                continue

            if isinstance(item, Batch):
                self.metrics.add("arroyo_worker_batches_recv")
                self.metrics.add("arroyo_worker_messages_recv", item.num_rows)
                self.metrics.add("arroyo_worker_bytes_recv", item.nbytes())
                if prof is None:
                    process(item, self.ctx, self.collector, input_index=idx)
                else:
                    if self.observe_input_keys and KEY_FIELD in item:
                        # keyed-insert boundary of the skew sketch
                        # (shuffling operators feed at the collector's
                        # shuffle boundary instead — never both)
                        prof.observe_keys(item.keys)
                    t0 = prof.begin()
                    process(item, self.ctx, self.collector, input_index=idx)
                    prof.end("process", t0)
                if self._terminal and item.num_rows:
                    self._observe_sink_latency(item)
                self.inbox.release(idx, item)
                self.metrics.queue_rem = self.metrics.queue_size - self.inbox.used_rows()
                continue

            sig: Signal = item
            if sig.kind == SignalKind.WATERMARK:
                holder.set(idx, sig.watermark)
                merged_watermark_changed()
            elif sig.kind == SignalKind.BARRIER:
                b = sig.barrier
                if current_barrier is not None and b.epoch < current_barrier.epoch:
                    # stale barrier of a subsumed epoch straggling in after
                    # the controller's stuck-checkpoint retry: a newer
                    # alignment is already in progress — joining the old one
                    # would skew this input's epoch tracking permanently
                    continue
                if current_barrier is not None and b.epoch > current_barrier.epoch:
                    # a retried epoch overtook a wedged alignment (the
                    # controller subsumed the old epoch after its
                    # checkpoint.timeout-ms): abandon it and replay the held
                    # traffic — the blocked inputs' own newer barriers sit at
                    # the front of their held queues and re-join below
                    current_barrier = None
                    barrier_inputs.clear()
                    blocked.clear()
                    for i in sorted(held):
                        pending.extend(held[i])
                    held.clear()
                if current_barrier is None:
                    current_barrier = b
                    self._resp("checkpoint_event", checkpoint_event=CheckpointEvent(
                        b.epoch, self.task_info.node_id, self.task_info.subtask_index,
                        int(time.time() * 1e6), "started_alignment"))
                barrier_inputs.add(idx)
                blocked.add(idx)
                try_complete_alignment()
            elif sig.kind == SignalKind.END_OF_DATA:
                finished.add(idx)
                holder.remove(idx)
                merged_watermark_changed()
                if len(finished) == self.n_inputs:
                    t0 = prof.begin() if prof is not None else None
                    op.on_close(self.ctx, self.collector)
                    if prof is not None:
                        prof.end("close", t0)
                        prof.refresh(force=True)
                    self.collector.broadcast(Signal.end_of_data())
                    break
                # a pending alignment may now be complete
                try_complete_alignment()
            elif sig.kind == SignalKind.STOP:
                # hard stop: state since the last barrier is NOT persisted
                self.finished_clean = False
                self.collector.broadcast(Signal.stop())
                break
            if stopping:
                # checkpoint-then-stop: everything after the stopping barrier
                # (held items, EndOfData) is post-snapshot and must NOT be
                # processed — it would mutate state past what was persisted.
                # Committing operators first wait for the engine's commit of
                # the stopping epoch (reference: CheckpointStopping sends
                # commits before workers exit) or their phase-1 data would
                # never be finalized.
                if op.is_committing() and stop_epoch is not None:
                    deadline = time.monotonic() + 30
                    committed = False
                    while time.monotonic() < deadline and not committed:
                        try:
                            msg = self.control_queue.get(timeout=0.1)
                        except _queue.Empty:
                            continue
                        if msg.kind == "commit" and msg.epoch is not None:
                            # honor EVERY commit (a straggling earlier epoch
                            # may land here too); done once the stopping
                            # epoch itself is committed
                            op.handle_commit(msg.epoch, self.ctx)
                            if msg.epoch == stop_epoch:
                                committed = True
                break
