"""Cross-worker data plane: remote edges over framed TCP.

Equivalent of crates/arroyo-worker/src/network_manager.rs: Quad-addressed
frames (src_node, src_subtask, dst_node, dst_subtask) multiplexed over one
TCP connection per worker pair, payloads being wire-codec batches or
signals (native/wire.py standing in for Arrow IPC). Backpressure is
end-to-end: the reader blocks on the destination task's bounded inbox,
TCP backpressures the sender (reference network_manager.rs:164-195).

The byte transport itself is the C++ host runtime (cpp/arroyo_host.cc
dp_* functions).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..batch import Batch
from ..faults import InjectedFault, fault_point
from ..native import MSG_DATA, MSG_SIGNAL, DataPlaneConn, DataPlaneListener
from ..native.wire import decode_batch, decode_signal, encode_batch, encode_signal
from ..types import Signal


class RemoteDest:
    """Duck-types TaskInbox.put for the Collector: items sent here travel
    over the data plane to the owning worker's real inbox."""

    def __init__(self, manager: "NetworkManager", worker: int,
                 quad: tuple[int, int, int, int]):
        self.manager = manager
        self.worker = worker
        self.quad = quad

    def put(self, input_index: int, item) -> None:
        # input_index is re-derived on the receiving side from the quad;
        # it is carried implicitly (registration maps quad -> flat index)
        # chaos hook: partition raises ConnectionError here (the sending
        # task dies exactly as if the peer vanished); drop/dup/delay model
        # the failure modes a correct protocol must NOT tolerate silently
        verdict = fault_point("network.send", key=f"{self.quad}",
                              worker=self.worker)
        if verdict is not None and verdict[0] == "drop":
            return
        conn = self.manager.conn_to(self.worker)
        if isinstance(item, Batch):
            payload, mtype = encode_batch(item), MSG_DATA
        elif isinstance(item, Signal):
            payload, mtype = encode_signal(item), MSG_SIGNAL
        else:
            raise TypeError(f"cannot ship {type(item)} over the data plane")
        conn.send(self.quad, mtype, payload)
        if verdict is not None and verdict[0] == "dup":
            conn.send(self.quad, mtype, payload)


class NetworkManager:
    """Per-worker endpoint: a listener plus lazy outgoing connections."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.listener = DataPlaneListener(host, port)
        self.host = host
        self.port = self.listener.port
        self.peers: dict[int, tuple[str, int]] = {}
        self._out: dict[int, DataPlaneConn] = {}
        self._out_lock = threading.Lock()
        # quad -> (inbox, flat_input_index)
        self._receivers: dict[tuple[int, int, int, int], tuple] = {}
        self._accept_thread: Optional[threading.Thread] = None
        self._reader_threads: list[threading.Thread] = []
        self._closed = False

    def set_peers(self, peers: dict[int, tuple[str, int]]) -> None:
        self.peers = dict(peers)

    def register_receiver(self, quad: tuple[int, int, int, int], inbox,
                          input_index: int) -> None:
        self._receivers[quad] = (inbox, input_index)

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dp-accept"
        )
        self._accept_thread.start()

    def conn_to(self, worker: int) -> DataPlaneConn:
        with self._out_lock:
            conn = self._out.get(worker)
        if conn is not None:
            return conn
        # dial outside the lock (LR105): a slow or unreachable peer must not
        # stall every other sender sharing this manager
        host, port = self.peers[worker]
        fresh = DataPlaneConn.connect(host, port)
        with self._out_lock:
            conn = self._out.get(worker)
            if conn is None:
                self._out[worker] = fresh
                return fresh
        fresh.close()  # lost the race; keep the established connection
        return conn

    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn = self.listener.accept()
            except Exception:  # noqa: BLE001 - listener closed
                return
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True, name="dp-reader"
            )
            t.start()
            self._reader_threads.append(t)

    def _read_loop(self, conn: DataPlaneConn) -> None:
        while True:
            try:
                got = conn.recv()
            except Exception:  # noqa: BLE001 - peer died; tasks see EOF-less stall
                return
            if got is None:
                return
            quad, mtype, payload = got
            try:
                verdict = fault_point("network.recv", key=f"{quad}", kind=mtype)
            except (InjectedFault, ConnectionError):
                return  # injected receive-side partition: reader dies
            if verdict is not None and verdict[0] == "drop":
                continue
            target = self._receivers.get(quad)
            if target is None:
                continue  # late frame for a finished task
            inbox, input_index = target
            if mtype == MSG_DATA:
                inbox.put(input_index, decode_batch(payload))
            else:
                inbox.put(input_index, decode_signal(payload))

    def close(self) -> None:
        self._closed = True
        self.listener.close()
        with self._out_lock:
            for conn in self._out.values():
                conn.close()
            self._out.clear()
