"""Cross-worker data plane: remote edges over framed TCP.

Equivalent of crates/arroyo-worker/src/network_manager.rs: Quad-addressed
frames (src_node, src_subtask, dst_node, dst_subtask) multiplexed over one
TCP connection per worker pair, payloads being wire-codec batches or
signals (native/wire.py standing in for Arrow IPC). Backpressure is
end-to-end: the reader blocks on the destination task's bounded inbox,
TCP backpressures the sender (reference network_manager.rs:164-195).

Frame coalescing (ISSUE 5): encoded DATA frames append to a per-connection
send buffer and one writev-style syscall carries many small batches; any
SIGNAL frame flushes the buffer first (in-line, same ordering guarantee as
the collector's coalescing layer), as does a byte cap or the periodic
flusher. Frame bytes and per-frame ordering are identical to the unbuffered
path — the receiver cannot tell the difference.

The byte transport itself is the C++ host runtime (cpp/arroyo_host.cc
dp_* functions).
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Optional

from ..obs.lockorder import make_lock

from ..batch import Batch
from ..config import config
from ..faults import InjectedFault, fault_point
from ..native import MSG_DATA, MSG_SIGNAL, DataPlaneConn, DataPlaneListener
from ..native.wire import decode_batch, decode_signal, encode_batch, encode_signal
from ..types import Signal

# MUST match cpp/arroyo_host.cc FrameHeader (6x uint32: quad, msg_type,
# len) — the coalesced path packs frames host-side so one write carries
# many; tests/test_coalesce.py round-trips python-packed frames through the
# C receiver, so any layout drift fails there before it can desync a stream
_HEADER = struct.Struct("=IIIIII")


class _SendBuffer:
    """Per-connection frame accumulator: many sub-threshold frames, one
    syscall. Writes happen under the conn's send lock so buffered writes
    and any direct ``conn.send`` never interleave mid-frame."""

    def __init__(self, conn: DataPlaneConn, max_bytes: int):
        self.conn = conn
        self.max_bytes = max_bytes
        self._chunks: list[bytes] = []
        self._bytes = 0
        self._lock = make_lock("_SendBuffer._lock")
        self._error: Optional[Exception] = None

    def append(self, quad, mtype: int, payload: bytes, flush: bool) -> None:
        frame = _HEADER.pack(*quad, mtype, len(payload)) + payload
        with self._lock:
            if self._error is not None:
                # latched: once a flush failed the stream is torn mid-frame;
                # every later append must fail too, never buffer-and-drop
                raise self._error
            self._chunks.append(frame)
            self._bytes += len(frame)
            if flush or self._bytes >= self.max_bytes:
                self._flush_locked()

    def flush_pending(self) -> None:
        """Drain whatever is buffered; write errors surface to the next
        sender (the periodic flusher has nobody to raise to)."""
        with self._lock:
            if self._chunks:
                try:
                    self._flush_locked()
                except Exception as e:
                    if self._error is None:  # _flush_locked latched already
                        self._error = e

    def _flush_locked(self) -> None:
        blob = b"".join(self._chunks)
        self._chunks, self._bytes = [], 0
        # the conn send lock is taken INSIDE the buffer lock on purpose:
        # a frame must hit the fd atomically and in append order, so the
        # buffer drains while both are held (direct conn.send callers take
        # only the inner lock — same order, no cycle)
        with self.conn._send_lock:
            view = memoryview(blob)
            while view:
                try:
                    # lint: waive LR403 — deliberate: frame atomicity and append order require writing under both locks; contenders here are exactly the senders whose frames must serialize
                    n = os.write(self.conn.fd, view)
                except OSError as e:
                    # latch HERE, not in flush_pending: the append path also
                    # reaches this point, and a torn stream must poison later
                    # appends no matter which caller hit the error first
                    self._error = ConnectionError(
                        f"data plane write failed: {e}")
                    raise self._error from e
                view = view[n:]


class RemoteDest:
    """Duck-types TaskInbox.put for the Collector: items sent here travel
    over the data plane to the owning worker's real inbox."""

    def __init__(self, manager: "NetworkManager", worker: int,
                 quad: tuple[int, int, int, int]):
        self.manager = manager
        self.worker = worker
        self.quad = quad

    def put(self, input_index: int, item) -> None:
        # input_index is re-derived on the receiving side from the quad;
        # it is carried implicitly (registration maps quad -> flat index)
        # chaos hook: partition raises ConnectionError here (the sending
        # task dies exactly as if the peer vanished); drop/dup/delay model
        # the failure modes a correct protocol must NOT tolerate silently
        verdict = fault_point("network.send", key=f"{self.quad}",
                              worker=self.worker)
        if verdict is not None and verdict[0] == "drop":
            return
        if isinstance(item, Batch):
            payload, mtype = encode_batch(item), MSG_DATA
        elif isinstance(item, Signal):
            payload, mtype = encode_signal(item), MSG_SIGNAL
        else:
            raise TypeError(f"cannot ship {type(item)} over the data plane")
        buf = self.manager.send_buffer_to(self.worker)
        if buf is not None:
            # signals flush in-line: a watermark/barrier frame must never
            # overtake buffered data frames, and never linger behind them
            buf.append(self.quad, mtype, payload, flush=mtype == MSG_SIGNAL)
            if verdict is not None and verdict[0] == "dup":
                buf.append(self.quad, mtype, payload, flush=mtype == MSG_SIGNAL)
            return
        conn = self.manager.conn_to(self.worker)
        conn.send(self.quad, mtype, payload)
        if verdict is not None and verdict[0] == "dup":
            conn.send(self.quad, mtype, payload)


class NetworkManager:
    """Per-worker endpoint: a listener plus lazy outgoing connections."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.listener = DataPlaneListener(host, port)
        self.host = host
        self.port = self.listener.port
        self.peers: dict[int, tuple[str, int]] = {}
        self._out: dict[int, DataPlaneConn] = {}
        self._out_lock = make_lock("NetworkManager._out_lock")
        # quad -> (inbox, flat_input_index)
        # concurrency: single-writer — receivers register during task wiring, before start() spawns readers; a late frame for an unknown quad is dropped by design
        self._receivers: dict[tuple[int, int, int, int], tuple] = {}
        self._accept_thread: Optional[threading.Thread] = None
        self._flush_thread: Optional[threading.Thread] = None
        self._reader_threads: list[threading.Thread] = []
        # concurrency: single-writer — monotonic stop flag set once by close(); a stale read costs one extra loop tick, never correctness
        self._closed = False
        c = config()
        self._coalesce = bool(c.get("engine.coalesce.enabled", True))
        self._co_max_bytes = int(c.get("engine.coalesce.max-bytes", 1 << 20))
        self._co_max_delay_s = float(
            c.get("engine.coalesce.max-delay-ms", 5)) / 1e3
        self._send_bufs: dict[int, _SendBuffer] = {}

    def set_peers(self, peers: dict[int, tuple[str, int]]) -> None:
        self.peers = dict(peers)

    def register_receiver(self, quad: tuple[int, int, int, int], inbox,
                          input_index: int) -> None:
        self._receivers[quad] = (inbox, input_index)

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dp-accept"
        )
        self._accept_thread.start()
        if self._coalesce:
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True, name="dp-flush"
            )
            self._flush_thread.start()

    def send_buffer_to(self, worker: int) -> Optional[_SendBuffer]:
        """The frame-coalescing buffer for this worker pair (None when
        coalescing is disabled)."""
        if not self._coalesce:
            return None
        with self._out_lock:
            buf = self._send_bufs.get(worker)
        if buf is not None:
            return buf
        conn = self.conn_to(worker)  # dial outside the lock
        with self._out_lock:
            buf = self._send_bufs.get(worker)
            if buf is None:
                buf = _SendBuffer(conn, self._co_max_bytes)
                self._send_bufs[worker] = buf
        return buf

    def _flush_loop(self) -> None:
        """Time-based safety flush: DATA frames not followed by a signal
        (the common flush trigger) still leave within max-delay-ms. Every
        non-empty buffer flushes each tick — an age test on a full-period
        sleep would let a just-missed frame wait ~2x the knob."""
        while not self._closed:
            time.sleep(self._co_max_delay_s)
            with self._out_lock:  # snapshot; flush outside the dict lock
                bufs = list(self._send_bufs.values())
            for buf in bufs:
                buf.flush_pending()

    def conn_to(self, worker: int) -> DataPlaneConn:
        with self._out_lock:
            conn = self._out.get(worker)
        if conn is not None:
            return conn
        # dial outside the lock (LR105): a slow or unreachable peer must not
        # stall every other sender sharing this manager
        host, port = self.peers[worker]
        fresh = DataPlaneConn.connect(host, port)
        with self._out_lock:
            conn = self._out.get(worker)
            if conn is None:
                self._out[worker] = fresh
                return fresh
        fresh.close()  # lost the race; keep the established connection
        return conn

    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn = self.listener.accept()
            except Exception:  # noqa: BLE001 - listener closed
                return
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True, name="dp-reader"
            )
            t.start()
            self._reader_threads.append(t)

    def _read_loop(self, conn: DataPlaneConn) -> None:
        while True:
            try:
                got = conn.recv()
            except Exception:  # noqa: BLE001 - peer died; tasks see EOF-less stall
                return
            if got is None:
                return
            quad, mtype, payload = got
            try:
                verdict = fault_point("network.recv", key=f"{quad}", kind=mtype)
            except (InjectedFault, ConnectionError):
                return  # injected receive-side partition: reader dies
            if verdict is not None and verdict[0] == "drop":
                continue
            target = self._receivers.get(quad)
            if target is None:
                continue  # late frame for a finished task
            inbox, input_index = target
            if mtype == MSG_DATA:
                inbox.put(input_index, decode_batch(payload))
            else:
                inbox.put(input_index, decode_signal(payload))

    def close(self) -> None:
        self._closed = True
        self.listener.close()
        with self._out_lock:  # snapshot; drain outside the dict lock
            bufs = list(self._send_bufs.values())
        for buf in bufs:
            # best-effort drain so frames sent just before close still land
            buf.flush_pending()
        with self._out_lock:
            self._send_bufs.clear()
            for conn in self._out.values():
                conn.close()
            self._out.clear()
