from .engine import Engine, construct_operator, register_operator, run_graph  # noqa: F401
from .queues import TaskInbox  # noqa: F401
from .task import Task, WatermarkHolder  # noqa: F401
