"""Engine: logical graph -> physical tasks -> running pipeline.

Equivalent of crates/arroyo-worker/src/engine.rs: Program::from_logical (:214,
node x parallelism -> SubtaskNode; Forward = 1:1 queue, Shuffle/LeftJoin/
RightJoin = full bipartite queues :319-357), Engine::start (:521), and
construct_operator (:770-901, OperatorName -> constructor mapping). Single
process; the multi-host data plane arrives with the C++/DCN runtime, while
keyed exchange inside a TPU slice is lowered separately (arroyo_tpu.parallel).

The engine also plays the reference controller's checkpoint-coordination role
for SINGLE-worker runs (job_controller/mod.rs:325 start_checkpoint,
checkpoint_state.rs): it injects ControlMessage::Checkpoint into source tasks,
collects per-subtask checkpoint metadata, and writes the job-level metadata
marker once every subtask reports. Under an ``assignment`` (multi-worker
mode) the engine is a pure participant: it relays per-subtask acks upward
through ``coordinator_events`` and accepts externally-injected commits via
``deliver_commit`` — epoch completion is owned by the control plane's
CheckpointCoordinator (controller/checkpoint_state.py), so no worker can
finalize phase 2 against an epoch another worker never made durable.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..batch import Schema
from ..config import config
from ..graph import EdgeType, Graph, Node, OpName
from ..operators.base import Operator, OperatorContext, SourceOperator
from ..operators.collector import Collector, OutEdge
from ..state.tables import (
    TableManager,
    cleanup_checkpoints,
    compact_job,
    latest_complete_checkpoint,
    write_job_checkpoint_metadata,
)
from ..obs.events import recorder as events_recorder
from ..obs.trace import recorder as trace_recorder
from ..obs.trace import now_us, timeline_report
from ..types import CheckpointBarrier, ControlMessage, ControlResp, TaskInfo
from .queues import TaskInbox
from .task import Task

# op name -> constructor(node_config, node, subtask ctx...) registered by the
# operator modules (reference engine.rs:867-879 construct_operator match).
_CONSTRUCTORS: dict[OpName, Callable[[dict], object]] = {}


def register_operator(op: OpName):
    def deco(fn):
        _CONSTRUCTORS[op] = fn
        return fn

    return deco


def construct_operator(op: OpName, cfg: dict):
    if op not in _CONSTRUCTORS:
        raise ValueError(f"no constructor registered for operator {op}")
    return _CONSTRUCTORS[op](cfg)


@dataclass(frozen=True)
class CheckpointWait:
    """Outcome of Engine.checkpoint_and_wait. Truthy only when the epoch
    actually completed, so ``assert eng.checkpoint_and_wait(...)`` keeps
    working — but callers can now tell a drained pipeline ("finished") from
    a stuck barrier ("timeout", with the subtasks that never acked)."""

    outcome: str  # "completed" | "finished" | "timeout"
    missing: tuple = ()  # (node_id, subtask) pairs unacked at timeout
    # timeout only: the epoch's trace timeline (obs.trace.timeline_report),
    # naming the exact subtask whose barrier never arrived / never acked —
    # a chaos failure asserting on this repr is self-diagnosing
    report: str = ""

    def __bool__(self) -> bool:
        return self.outcome == "completed"

    def __repr__(self) -> str:
        if self.outcome == "timeout" and self.missing:
            base = (f"CheckpointWait(timeout, never acked: "
                    f"{list(self.missing)})")
            return f"{base}\n{self.report}" if self.report else base
        return f"CheckpointWait({self.outcome})"


class Engine:
    def __init__(
        self,
        graph: Graph,
        job_id: str = "job",
        storage_url: Optional[str] = None,
        restore_epoch: Optional[int] = None,
        assignment: Optional[dict] = None,
        worker_index: int = 0,
        network=None,
    ):
        """assignment: {(node_id, subtask) -> worker_index} places subtasks
        on workers (reference compute_assignments, states/scheduling.rs:56);
        None runs everything in this engine. Remote edges ride ``network``
        (engine.network.NetworkManager over the C++ data plane)."""
        # chaos: a configured fault plan (faults.plan / ARROYO_TPU__FAULTS__
        # PLAN) activates with fresh counters per engine incarnation, so a
        # restarted worker replays its faults deterministically
        from ..faults import install_from_config

        install_from_config()
        # plan fingerprint of the logical (pre-chaining) graph — the same
        # graph the control plane planned, so controller and worker agree on
        # the hash stamped into checkpoint metadata regardless of the
        # chaining setting. Computed before chain_graph rewrites node ids.
        self.plan_hash = self._fingerprint(graph)
        if config().get("pipeline.chaining.enabled"):
            from ..optimizer import chain_graph

            graph = chain_graph(graph)
        if assignment is not None:
            # assignments computed against a differently-chained graph would
            # silently place fused subtasks on worker 0; reject instead
            unknown = {nid for nid, _ in assignment} - set(graph.nodes)
            if unknown:
                raise ValueError(
                    f"assignment references node ids not in the (post-chaining) "
                    f"graph: {sorted(unknown)}; compute assignments against the "
                    f"same pipeline.chaining.enabled setting"
                )
        self.graph = graph
        self.job_id = job_id
        self.storage_url = storage_url or config().get("checkpoint.storage-url")
        self.restore_epoch = restore_epoch
        self.assignment = assignment
        self.worker_index = worker_index
        self.network = network
        # multi-worker mode: epoch completion is controller-owned; this
        # engine only relays acks up and accepts injected commits
        self.coordinated = assignment is not None
        self.coordinator_events: "_queue.Queue[dict]" = _queue.Queue()
        self._committed_through = restore_epoch or 0
        self.delivered_commits: list[int] = []
        # stable numeric node ids for Quad addressing
        self._node_index = {nid: i for i, nid in enumerate(sorted(graph.nodes))}
        self.resp_queue: "_queue.Queue[ControlResp]" = _queue.Queue()
        # concurrency: single-writer — tasks/_inboxes are populated by build() before start() spawns the collector; Thread.start() is the happens-before edge, after which nobody mutates the dicts
        self.tasks: dict[tuple[str, int], Task] = {}
        self._inboxes: dict[tuple[str, int], TaskInbox] = {}  # concurrency: single-writer — same build()-then-start() discipline as tasks
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._finished_tasks: set[tuple[str, int]] = set()
        # the subset that drained CLEANLY (graceful EOF / checkpoint-then-
        # stop): only these have final/durable state and may stand in for
        # epoch coverage; stop/abort exits must not, or an epoch could go
        # "complete" with a subtask's snapshot missing and a restore would
        # replay its source from zero
        self._clean_finished: set[tuple[str, int]] = set()
        # concurrency: single-writer — appended only by the collector thread; join()'s unlocked reads are GIL-atomic list snapshots (truthiness + element 0)
        self._failed: list[ControlResp] = []
        self._checkpoints: dict[int, dict[tuple[str, int], dict]] = {}
        self._completed_epochs: set[int] = set()
        self._resp_thread: Optional[threading.Thread] = None
        # concurrency: single-writer — set by build() before the collector thread exists (see tasks above)
        self._n_tasks = 0
        self.restored_watermark: Optional[int] = None
        # triggers that arrived before build() populated the source tasks —
        # replayed by start(); without this, a checkpoint trigger racing a
        # slow build (cold compile, big restore) is silently LOST and the
        # epoch wedges from birth
        self._running = False
        self._pending_triggers: list[tuple[int, bool]] = []
        # set by _abort(): distinguishes a torn-down engine from a drained
        # one — an externally-killed worker must not report "finished"
        self._aborted = False
        # armed by build() when restoring through an evolution mapping in
        # single-worker mode: the first durable epoch is the blue/green
        # cutover barrier (commits withheld until then)
        # concurrency: single-writer — armed by build() pre-thread; cleared only by the collector under _lock
        self._evolve_cutover_pending = False
        # obs relay (worker subprocesses only; relay_obs set by the worker
        # CLI): epoch-lifecycle spans AND structured job events recorded in
        # this process are forwarded over the JSON-lines protocol so the
        # CONTROLLER's recorders hold the whole job's timeline + event feed.
        # All worker->controller streams drain through ONE helper
        # (drain_relay) so a new event kind never grows a new hand-rolled
        # drain with its own ordering bugs.
        self.relay_obs = False
        self.span_events: "_queue.Queue[dict]" = _queue.Queue()
        # relay cursors: job-event seq and epochs already reported
        self._relay_event_seq = events_recorder.last_seq(job_id)
        self._relay_reported_epochs: set[int] = set()

    def _span(self, epoch: int, event: str, node: Optional[str] = None,
              subtask: Optional[int] = None, worker: Optional[int] = None,
              t_us: Optional[int] = None) -> None:
        t = now_us() if t_us is None else int(t_us)
        trace_recorder.record(self.job_id, epoch, event, node, subtask,
                              worker, t)
        if self.relay_obs:
            self.span_events.put({
                "event": "span", "epoch": epoch, "name": event, "node": node,
                "subtask": subtask, "worker": worker, "t_us": t,
            })

    def drain_relay(self, include_metrics: bool = False) -> list[dict]:
        """ONE drain for every worker->controller relay stream, in the
        order the controller must observe them (the PR 6 drain-ordering bug
        class, fixed structurally):

          1. epoch-lifecycle span events — must land in the controller's
             trace recorder BEFORE the coordinator ack that completes
             global coverage, or the persisted epoch trace misses the
             final ack span;
          2. structured job events (obs.events) recorded in this process
             since the last drain — a task's OPERATOR_PANIC precedes the
             worker's terminal "failed" event, which the CLI loop emits
             only after draining;
          3. the per-second metrics snapshot (caller-throttled: it rides
             the heartbeat cadence and its chaos drop);
          4. coordinator acks / completed epochs, strictly last.

        A fourth relayed event kind slots in here — never as a fourth
        hand-rolled drain in the CLI loop."""
        out: list[dict] = []
        while True:
            try:
                out.append(self.span_events.get_nowait())
            except _queue.Empty:
                break
        if self.relay_obs:
            evs = events_recorder.events(self.job_id,
                                         after_seq=self._relay_event_seq)
            if evs:
                self._relay_event_seq = evs[-1]["seq"]
                out.extend({"event": "log", "data": e} for e in evs)
        if include_metrics:
            from ..metrics import registry as _metrics_registry

            out.append({"event": "metrics",
                        "data": _metrics_registry.job_metrics(self.job_id)})
        if self.coordinated:
            while True:
                try:
                    out.append(self.coordinator_events.get_nowait())
                except _queue.Empty:
                    break
        else:
            with self._lock:
                completed = sorted(
                    self._completed_epochs - self._relay_reported_epochs)
            for ep in completed:
                self._relay_reported_epochs.add(ep)
                out.append({"event": "checkpoint_completed", "epoch": ep})
        return out

    # -------------------------------------------------------------- building

    @staticmethod
    def _fingerprint(graph: Graph) -> Optional[str]:
        """analysis.plan_diff.plan_fingerprint, degraded to None when the
        analysis package cannot run here (it instantiates operators; a
        worker built before _load_operators simply skips stamping rather
        than stamping a hash the controller would never match)."""
        try:
            from ..analysis.plan_diff import plan_fingerprint

            return plan_fingerprint(graph)
        except Exception:
            return None

    def _is_mine(self, nid: str, sub: int) -> bool:
        if self.assignment is None:
            return True
        return self.assignment.get((nid, sub), 0) == self.worker_index

    def _worker_of(self, nid: str, sub: int) -> int:
        if self.assignment is None:
            return self.worker_index
        return self.assignment.get((nid, sub), 0)

    def build(self) -> None:
        g = self.graph
        self.evolution_mapping: Optional[dict] = None
        if self.restore_epoch is not None:
            from ..state.tables import (read_evolution_mapping,
                                        read_job_checkpoint_metadata)

            meta = read_job_checkpoint_metadata(
                self.storage_url, self.job_id, self.restore_epoch
            )
            mapping = read_evolution_mapping(
                self.storage_url, self.job_id, self.restore_epoch
            )
            # plan-fingerprint gate (degrade-not-corrupt): checkpointed
            # bytes are typed by the plan that wrote them. A hash mismatch
            # without a proven evolution mapping means this graph would
            # misread them — fail loudly instead.
            meta_hash = (meta or {}).get("plan_hash")
            if (meta_hash and self.plan_hash
                    and meta_hash != self.plan_hash):
                if mapping is None:
                    raise RuntimeError(
                        f"checkpoint epoch {self.restore_epoch} was written "
                        f"by plan {meta_hash} but this graph is plan "
                        f"{self.plan_hash} and no evolution mapping covers "
                        f"the change — restoring would misread state; run "
                        f"the evolve API so the plan-diff pass can prove "
                        f"(or reject) the carry-over"
                    )
                if (mapping.get("old_plan_hash") != meta_hash
                        or mapping.get("new_plan_hash") != self.plan_hash):
                    raise RuntimeError(
                        f"evolution mapping for epoch {self.restore_epoch} "
                        f"covers {mapping.get('old_plan_hash')} -> "
                        f"{mapping.get('new_plan_hash')} but the restore is "
                        f"{meta_hash} -> {self.plan_hash}; refusing a "
                        f"mapping proven for a different plan pair"
                    )
            if mapping is not None:
                self.evolution_mapping = mapping
                # blue/green: a single-worker engine self-commits, so IT
                # owns the cutover barrier — withhold phase-2 commits
                # until the evolved plan's first epoch goes durable
                # (coordinated sets gate in the controller instead)
                self._evolve_cutover_pending = not self.coordinated
            # operators the epoch holds state for that this graph lacks:
            # under an evolution mapping those explicitly dropped or carried
            # into a renamed successor are expected; anything else is a
            # silent state drop and rejected
            stale = set((meta or {}).get("operators", ())) - set(g.nodes)
            if mapping is not None:
                expected_gone = set(mapping.get("dropped", ()))
                expected_gone |= {
                    str(m.get("from")) for m in mapping.get("nodes", {}).values()
                    if m.get("from")
                }
                stale -= expected_gone
            if stale:
                raise RuntimeError(
                    f"checkpoint epoch {self.restore_epoch} holds state for "
                    f"operators {sorted(stale)} that do not exist in this graph "
                    f"— restoring across a pipeline.chaining.enabled change (or "
                    f"a graph edit) would silently drop their state"
                )
        queue_size = config().get("worker.queue-size")
        # flat-input layout per node: in-edge order, then upstream subtask
        in_layout: dict[str, list[tuple[int, int]]] = {}  # node -> [(edge_i, parallelism)]
        for nid, node in g.nodes.items():
            edges = g.in_edges(nid)
            in_layout[nid] = [(i, g.nodes[e.src].parallelism) for i, e in enumerate(edges)]
            n_inputs = sum(p for _, p in in_layout[nid])
            for s in range(node.parallelism):
                if n_inputs and self._is_mine(nid, s):
                    self._inboxes[(nid, s)] = TaskInbox(n_inputs, queue_size)

        # register network receivers for my tasks' remote inputs. Quads are
        # (edge_index, src_subtask, dst_node, dst_subtask) — the EDGE index
        # (not src node) disambiguates parallel edges between one node pair
        # (e.g. self-join / union-with-self shapes).
        edge_index = {id(e): i for i, e in enumerate(g.edges)}
        if self.network is not None:
            for nid, node in g.nodes.items():
                base = 0
                for e in g.in_edges(nid):
                    src_p = g.nodes[e.src].parallelism
                    for s in range(node.parallelism):
                        if not self._is_mine(nid, s):
                            continue
                        for u in range(src_p):
                            if not self._is_mine(e.src, u):
                                quad = (edge_index[id(e)], u,
                                        self._node_index[nid], s)
                                self.network.register_receiver(
                                    quad, self._inboxes[(nid, s)], base + u
                                )
                    base += src_p
            self.network.start()

        for nid, node in g.nodes.items():
            in_edges = g.in_edges(nid)
            n_inputs = sum(g.nodes[e.src].parallelism for e in in_edges)

            def edge_of_input(i, _edges=in_edges, _g=g):
                base = 0
                for ei, e in enumerate(_edges):
                    p = _g.nodes[e.src].parallelism
                    if i < base + p:
                        return (ei, i - base)
                    base += p
                raise IndexError(i)

            for s in range(node.parallelism):
                if not self._is_mine(nid, s):
                    continue
                ti = TaskInfo(self.job_id, nid, node.op.value, s, node.parallelism)
                out_edges = []
                for e in g.out_edges(nid):
                    dst_node = g.nodes[e.dst]
                    # flat input base for this edge at the destination
                    base = 0
                    for de in g.in_edges(e.dst):
                        if de is e:
                            break
                        base += g.nodes[de.src].parallelism
                    dests = []
                    for d in range(dst_node.parallelism):
                        if self._is_mine(e.dst, d):
                            dests.append(self._inboxes[(e.dst, d)])
                        else:
                            from .network import RemoteDest

                            quad = (edge_index[id(e)], s,
                                    self._node_index[e.dst], d)
                            dests.append(RemoteDest(
                                self.network, self._worker_of(e.dst, d), quad
                            ))
                    idxs = [base + s] * dst_node.parallelism
                    etype = e.edge_type
                    if etype == EdgeType.FORWARD and dst_node.parallelism != node.parallelism:
                        etype = EdgeType.SHUFFLE
                    out_edges.append(OutEdge(etype, dests, idxs))
                collector = Collector(out_edges, s)
                tm = TableManager(ti, self.storage_url)
                operator = construct_operator(node.op, node.config)
                ctx = OperatorContext(
                    ti,
                    out_schema=g.out_edges(nid)[0].schema if g.out_edges(nid) else None,
                    table_manager=tm,
                    in_edge_of_input=edge_of_input,
                )
                if self.restore_epoch is not None:
                    node_map = (self.evolution_mapping or {}).get(
                        "nodes", {}).get(nid)
                    wm = tm.restore(self.restore_epoch, operator.tables(),
                                    mapping=node_map)
                    if wm is not None:
                        self.restored_watermark = (
                            wm if self.restored_watermark is None else min(self.restored_watermark, wm)
                        )
                task = Task(
                    ti,
                    operator,
                    self._inboxes.get((nid, s)),
                    collector,
                    ctx,
                    self.resp_queue,
                    n_inputs=n_inputs,
                )
                self.tasks[(nid, s)] = task
        self._n_tasks = len(self.tasks)

    # -------------------------------------------------------------- running

    def start(self) -> None:
        if not self.tasks:
            self.build()
        self._resp_thread = threading.Thread(target=self._collect_resps, daemon=True)
        self._resp_thread.start()
        # start sinks-to-sources so consumers are ready before producers
        for node in reversed(self.graph.topo_order()):
            for s in range(node.parallelism):
                task = self.tasks.get((node.node_id, s))
                if task is not None:  # remote subtasks belong to other workers
                    task.start()
        with self._lock:
            self._running = True
            pending, self._pending_triggers = self._pending_triggers, []
        for epoch, then_stop in pending:
            self.trigger_checkpoint(epoch, then_stop=then_stop)

    def _collect_resps(self) -> None:
        while True:
            try:
                resp = self.resp_queue.get(timeout=0.25)
            except _queue.Empty:
                with self._lock:
                    if len(self._finished_tasks) + len(self._failed) >= self._n_tasks and self._n_tasks:
                        return
                continue
            if resp.kind == "checkpoint_event" and resp.checkpoint_event:
                ce = resp.checkpoint_event
                name = {"started_alignment": "align_start",
                        "started_checkpointing": "snapshot_start"}.get(
                            ce.event_type)
                if name:
                    self._span(ce.checkpoint_epoch, name, node=resp.node_id,
                               subtask=resp.subtask_index,
                               t_us=ce.time_micros)
                continue
            if resp.kind == "checkpoint_completed":
                self._span(resp.epoch, "ack", node=resp.node_id,
                           subtask=resp.subtask_index)
            with self._lock:
                key = (resp.node_id, resp.subtask_index)
                if resp.kind == "task_finished":
                    self._finished_tasks.add(key)
                    if resp.clean:
                        self._clean_finished.add(key)
                        if self.coordinated:
                            # only CLEAN drains are relayed as coverage;
                            # stop/abort exits have no durable final state
                            self.coordinator_events.put({
                                "event": "subtask_finished",
                                "node": key[0], "subtask": key[1],
                            })
                    self._finish_ready_epochs()
                elif resp.kind == "task_failed":
                    self._failed.append(resp)
                    # propagate: unstick every surviving task so producers
                    # blocked on a dead consumer's row budget unwind
                    # (reference: ControlResp::TaskFailed -> controller stops
                    # the job; here the embedded engine aborts directly)
                    self._abort()
                elif resp.kind == "checkpoint_completed":
                    ep = self._checkpoints.setdefault(resp.epoch, {})
                    ep[key] = resp.subtask_metadata
                    if self.coordinated:
                        from ..state.integrity import fold_integrity

                        # the subtask's artifact envelopes ride the ack so
                        # the controller's marker can fold the per-epoch
                        # integrity manifest without re-reading storage
                        self.coordinator_events.put({
                            "event": "subtask_acked", "epoch": resp.epoch,
                            "node": key[0], "subtask": key[1],
                            "integrity": fold_integrity(
                                [resp.subtask_metadata or {}]),
                        })
                    self._finish_ready_epochs()
                self._cond.notify_all()

    def _finish_ready_epochs(self) -> None:
        """An epoch is complete once every task has snapshotted it or
        finished outright (a drained source can't take part in a barrier —
        its state is final; reference CheckpointState handles TaskFinished
        the same way). Caller holds the lock.

        Only the single-worker engine decides this locally. In assignment
        mode the per-subtask acks were already relayed upward (above): the
        controller's CheckpointCoordinator owns global coverage, writes the
        job-level metadata marker, and injects commits via deliver_commit —
        a local task count can never prematurely finalize an epoch that
        other workers are still snapshotting."""
        if self.coordinated:
            return
        for epoch, ep in self._checkpoints.items():
            if epoch in self._completed_epochs or not ep:
                continue
            covered = set(ep) | self._clean_finished
            if len(covered) >= self._n_tasks:
                extra = {"operators": list({k[0] for k in ep})}
                if self.plan_hash:
                    extra["plan_hash"] = self.plan_hash
                from ..state.integrity import fold_integrity

                integ = fold_integrity(m for m in ep.values() if m)
                if integ:
                    extra["integrity"] = integ
                write_job_checkpoint_metadata(
                    self.storage_url, self.job_id, epoch, extra,
                )
                self._span(epoch, "metadata_durable")
                if self._evolve_cutover_pending:
                    # blue/green cutover barrier (single-worker live
                    # evolution): this is the evolved plan's first durable
                    # epoch — it proves the new set caught up past the
                    # carried offsets. The `evolve_cutover` chaos site
                    # fires between durability and the commit release.
                    self._evolve_cutover_pending = False
                    from ..faults import fault_point

                    try:
                        fault_point("evolve_cutover", epoch=epoch,
                                    key=self.job_id)
                    except Exception as exc:  # noqa: BLE001 - injected
                        # crash AT the barrier: the epoch's metadata is
                        # durable but every commit stays withheld. The
                        # restarted incarnation restores from THIS epoch
                        # (same plan hash, no mapping needed) and the
                        # sink re-commits its staged output idempotently
                        # — exactly one committed lineage
                        self._failed.append(ControlResp(
                            kind="task_failed", node_id="<evolve_cutover>",
                            error=f"injected crash at the evolve cutover "
                                  f"barrier (epoch {epoch}): {exc}"))
                        self._abort()
                        return
                self._completed_epochs.add(epoch)
                # two-phase commit: metadata is durable, tell committing
                # sinks to finalize (reference send_commit_messages,
                # job_controller/mod.rs:838)
                for key, task in self.tasks.items():
                    if key in self._finished_tasks:
                        continue
                    opv = getattr(task, "operator", None)
                    if opv is not None and getattr(opv, "is_committing", lambda: False)():
                        # lint: waive LR403 — control_queue is an unbounded queue.Queue; put() never blocks, so holding _lock across it cannot stall
                        task.control_queue.put(
                            ControlMessage(kind="commit", epoch=epoch)
                        )
                self._span(epoch, "commit_delivered", worker=self.worker_index)

    def deliver_commit(self, epoch: int) -> None:
        """Phase-2 entry point in assignment mode: the control plane calls
        this once ``epoch``'s job-level metadata is durable across ALL
        workers. Marks the epoch (and any earlier ones whose commit message
        was lost — chaos site ``commit`` drops them on purpose) complete and
        forwards per-epoch commit messages to local committing operators, in
        epoch order. Cumulative delivery is what makes a dropped phase-2
        message re-delivered on the next epoch instead of lost."""
        to_commit: list[tuple[Task, int]] = []
        with self._lock:
            if epoch <= self._committed_through:
                return
            lo = self._committed_through
            self._committed_through = epoch
            # the carried epoch is durable by the coordinator's ordering
            # invariant; intermediates are marked only if this worker acked
            # them — an epoch the watchdog subsumed (and nobody acked here)
            # must not surface as "completed" to compact()/cleanup() callers
            self._completed_epochs.add(epoch)
            delivered = []
            for e in sorted(self._checkpoints):
                if not (lo < e <= epoch):
                    continue
                self._completed_epochs.add(e)
                self.delivered_commits.append(e)
                delivered.append(e)
                for key, task in self.tasks.items():
                    if key not in self._checkpoints[e] or key in self._finished_tasks:
                        continue
                    opv = getattr(task, "operator", None)
                    if opv is not None and getattr(opv, "is_committing", lambda: False)():
                        to_commit.append((task, e))
            self._cond.notify_all()
        for task, e in to_commit:
            task.control_queue.put(ControlMessage(kind="commit", epoch=e))
        # stamp every epoch this call made durable-and-committed, not just
        # the carried one: a re-delivered dropped commit for epoch E must
        # close E's commit span or the trace shows E wedged forever
        for e in delivered:
            if e != epoch:
                self._span(e, "commit_delivered", worker=self.worker_index)
                # a lost phase-2 commit recovered by cumulative delivery is
                # an operational fact worth a feed entry, not just a span
                events_recorder.record(
                    self.job_id, "WARN", "COMMIT_REDELIVERED",
                    message=f"phase-2 commit for epoch {e} re-delivered "
                            f"cumulatively with epoch {epoch}",
                    worker=self.worker_index, epoch=e)
        self._span(epoch, "commit_delivered", worker=self.worker_index)

    def heartbeat(self) -> float:
        """Liveness derived from actual engine progress: the stalest
        still-running task's last run-loop beat (tasks beat every loop
        iteration, sources via poll_control, backpressured producers from
        the inbox wait loop). A wedged subtask — hung in an operator or a
        stalled storage call — stops beating and ages this value out, which
        is what lets the controller's heartbeat timeout catch a hung
        embedded engine (a thread's mere existence proves nothing). The
        flip side: one process_batch call is one beat interval, so
        ``pipeline.worker-heartbeat-timeout-ms`` must stay above the
        worst-case single-batch latency (the 30s default leaves plenty of
        headroom for cold jit compiles and retry backoff)."""
        beats = []
        with self._lock:
            for key, t in self.tasks.items():
                if key in self._finished_tasks:
                    continue
                if t.thread is not None and t.thread.is_alive():
                    beats.append(t.last_progress)
        return min(beats) if beats else time.monotonic()

    # -------------------------------------------------------------- control

    def source_tasks(self) -> list[Task]:
        return [t for t in self.tasks.values() if t.is_source]

    def trigger_checkpoint(self, epoch: int, then_stop: bool = False) -> None:
        """Reference job_controller/mod.rs:325: checkpoint starts at sources.
        Triggers arriving before the engine is running are buffered and
        replayed by start() — never dropped."""
        self._span(epoch, "trigger")
        with self._lock:
            if not self._running:
                self._pending_triggers.append((epoch, then_stop))
                return
        barrier = CheckpointBarrier(epoch=epoch, timestamp=int(time.time() * 1e6), then_stop=then_stop)
        for t in self.source_tasks():
            t.control_queue.put(ControlMessage(kind="checkpoint", barrier=barrier))

    def checkpoint_and_wait(self, epoch: int, timeout: float = 60.0,
                            then_stop: bool = False) -> CheckpointWait:
        """Trigger ``epoch`` and wait. Returns a CheckpointWait whose
        outcome distinguishes the three exits callers used to have to
        guess apart: "completed" (truthy — every subtask snapshotted; in
        assignment mode, globally durable and committed), "finished" (the
        pipeline drained before the barrier — a stop, not a failure), and
        "timeout" (a stuck barrier, with the subtasks that never acked in
        ``missing`` for the diagnostic)."""
        self.trigger_checkpoint(epoch, then_stop=then_stop)
        deadline = time.monotonic() + timeout
        with self._lock:
            while epoch not in self._completed_epochs:
                if self._failed:
                    raise RuntimeError(f"task failed during checkpoint: {self._failed[0].error}")
                if len(self._finished_tasks) >= self._n_tasks:
                    return CheckpointWait("finished")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    acked = set(self._checkpoints.get(epoch, ()))
                    missing = tuple(sorted(
                        set(self.tasks) - acked - self._finished_tasks))
                    expected = set(self.tasks) - self._finished_tasks
                    report = timeline_report(
                        self.job_id, epoch,
                        trace_recorder.events(self.job_id, epoch),
                        expected=expected)
                    return CheckpointWait("timeout", missing, report)
                self._cond.wait(timeout=min(remaining, 0.5))
        return CheckpointWait("completed")

    def compact(self, epoch: int) -> int:
        """Merge the epoch's per-subtask state shards (reference: controller
        compact_state trigger, job_controller/mod.rs:382). Safe only for
        completed epochs."""
        with self._lock:
            if epoch not in self._completed_epochs:
                raise ValueError(f"epoch {epoch} is not a completed checkpoint")
        return compact_job(self.storage_url, self.job_id, epoch)

    def cleanup(self, min_epoch: int) -> int:
        """Drop checkpoints below min_epoch (controller epoch GC). Refuses
        to delete past the newest restorable checkpoint."""
        with self._lock:
            newest = max(self._completed_epochs, default=None)
        if newest is None:
            newest = latest_complete_checkpoint(self.storage_url, self.job_id)
        if newest is None or min_epoch > newest:
            raise ValueError(
                f"cleanup(min_epoch={min_epoch}) would delete every restorable "
                f"checkpoint (newest complete epoch: {newest})"
            )
        return cleanup_checkpoints(self.storage_url, self.job_id, min_epoch)

    def stop(self) -> None:
        for t in self.source_tasks():
            # lint: waive LR403 — control_queue is an unbounded queue.Queue; put() never blocks (flagged via the _abort -> stop() reach under _lock)
            t.control_queue.put(ControlMessage(kind="stop"))

    def _abort(self) -> None:
        """Hard-stop after a task failure: stop sources and close every
        inbox so blocked producers/consumers exit."""
        self._aborted = True
        self.stop()
        for inbox in self._inboxes.values():
            inbox.close()

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            if self._failed:
                # give surviving tasks a moment to unwind after the abort
                for t in self.tasks.values():
                    t.join(2.0)
                raise RuntimeError(f"pipeline task failed:\n{self._failed[0].error}")
            alive = [t for t in self.tasks.values() if t.thread and t.thread.is_alive()]
            if not alive:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(alive)} tasks still running after join timeout"
                )
            alive[0].join(0.2)
        # every task thread has exited, but the final task_finished /
        # task_failed responses may still be in flight on the resp queue —
        # wait for the accounting to catch up, or a failure posted just
        # before a thread died would be silently swallowed and a crashed
        # pipeline would report success
        catchup = time.monotonic() + 5.0
        with self._lock:
            while (self._n_tasks
                   and len(self._finished_tasks) + len(self._failed) < self._n_tasks
                   and time.monotonic() < catchup):
                self._cond.wait(timeout=0.1)
        if self._failed:
            raise RuntimeError(f"pipeline task failed:\n{self._failed[0].error}")

    def run_to_completion(self, timeout: Optional[float] = 120.0) -> None:
        self.start()
        self.join(timeout)


def run_graph(graph: Graph, job_id: str = "job", timeout: float = 120.0, **kw) -> Engine:
    """Convenience: build, run to completion, return the finished engine."""
    eng = Engine(graph, job_id=job_id, **kw)
    eng.run_to_completion(timeout)
    return eng
