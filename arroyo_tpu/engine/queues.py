"""Task inbox with per-input row-budget backpressure.

The reference gives every input edge an unbounded channel guarded by an
atomic row-count budget (crates/arroyo-operator/src/context.rs:113-205
``batch_bounded``; default ``worker.queue-size = 8192`` rows). Here each task
owns ONE multiplexed inbox; producers tag items with their flat input index
and block while that input's outstanding row budget is exhausted. Budget is
released when the consumer finishes processing the item, so batches held for
barrier alignment keep exerting backpressure upstream — reproducing aligned-
checkpoint backpressure (operator.rs:966-975).

Signals (watermarks, barriers, stop, end-of-data) never block: they must be
able to overtake a full queue exactly as in the reference.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Union

from ..batch import Batch
from ..faults import fault_point
from ..obs.lockorder import make_lock
from ..types import Signal

QueueItem = Union[Batch, Signal]


class TaskInbox:
    def __init__(self, n_inputs: int, row_budget: int):
        self.n_inputs = max(n_inputs, 1)
        self.row_budget = row_budget
        # items carry their enqueue wall time: the consumer-side pop feeds
        # the queue-transit latency histogram (coalescing instrumentation)
        self._queue: deque[tuple[int, QueueItem, float]] = deque()
        self._used = [0] * self.n_inputs
        self._lock = make_lock("TaskInbox._lock")
        self._not_empty = make_lock("TaskInbox._lock", kind="cond",
                                    lock=self._lock)
        self._budget_freed = make_lock("TaskInbox._lock", kind="cond",
                                       lock=self._lock)
        self._closed = False
        self.metrics = None  # TaskMetrics of the consuming task

    def put(self, input_index: int, item: QueueItem) -> None:
        """Blocks while this input's row budget is exhausted (data only)."""
        # chaos hook: delay models a stalled consumer (backpressure builds
        # upstream through the blocked producer); fail kills the producer
        fault_point("queue.put", input=input_index)
        rows = item.num_rows if isinstance(item, Batch) else 0
        # healthy-but-backpressured producers must keep their liveness beat
        # (Task sets this hook on its own thread); a task truly hung inside
        # an operator never reaches this wait loop, so it still goes stale
        beat = getattr(threading.current_thread(), "arroyo_beat", None)
        with self._lock:
            if rows:
                while (
                    self._used[input_index] > 0
                    and self._used[input_index] + rows > self.row_budget
                    and not self._closed
                ):
                    if beat is not None:
                        beat()
                    self._budget_freed.wait(timeout=0.5)
            if self._closed:
                return
            self._used[input_index] += rows
            self._queue.append((input_index, item, time.monotonic()))
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[tuple[int, QueueItem]]:
        """Pop next item; None on timeout or close-with-empty-queue."""
        with self._lock:
            if not self._queue:
                self._not_empty.wait(timeout=timeout)
            if not self._queue:
                return None
            idx, item, t_enq = self._queue.popleft()
        if self.metrics is not None and isinstance(item, Batch):
            self.metrics.queue_transit.observe(time.monotonic() - t_enq)
        return idx, item

    def release(self, input_index: int, item: QueueItem) -> None:
        """Consumer finished processing; return the rows to the budget."""
        if not isinstance(item, Batch):
            return
        with self._lock:
            self._used[input_index] -= item.num_rows
            self._budget_freed.notify_all()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._budget_freed.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def used_rows(self) -> int:
        with self._lock:
            return sum(self._used)
